"""Chunk-parity suite for the 10M-row training data path (ISSUE 3):

- streamed pyarrow record-batch CSV ingest == monolithic parse,
  column-exact (and a truncated stream fails loudly — never a short
  frame);
- ``Frame.binned`` (column-block binning straight from Frame columns)
  == ``apply_bins_jit(frame.to_matrix(...), ...)`` bitwise, plus the
  host-chunked variant the out-of-core trainer consumes;
- out-of-core chunk-streamed GBM == the resident-chunk mode bitwise
  (the staging machinery must not touch a single bit), == the
  monolithic fused path bitwise where the histogram sums are exact
  (single gaussian round on a ±0.5-gradient response), and close in
  float elsewhere;
- the jitted-scorer cache LRU cap (H2O_TPU_SCORER_CACHE_MAX);
- the device-gather Vec.select_rows fold-slice path.
"""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.frame import Frame
from h2o_kubernetes_tpu.frame.parse import import_file
from h2o_kubernetes_tpu.models import GBM
from h2o_kubernetes_tpu.models.tree.binning import (apply_bins_jit,
                                                    bin_frame_host_chunks,
                                                    fit_bins)
from tools import datasets as D


def _mixed_frame(n=1800, seed=3):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = (rng.exponential(2.0, size=n)).astype(np.float32)
    x2[rng.random(n) < 0.05] = np.nan
    c = np.array(["u", "v", "w"])[rng.integers(0, 3, size=n)]
    hc = rng.integers(0, 400, size=n).astype(np.float32)  # > n_bins levels
    y = np.where(x1 + 0.4 * x2 * 0 + (c == "u") +
                 rng.normal(scale=0.6, size=n) > 0.5, "yes", "no")
    return h2o.Frame.from_arrays(
        {"x1": x1, "x2": x2, "c": c, "hc": hc, "y": y},
        domains={"hc": [f"L{i}" for i in range(400)]})


# ---------------------------------------------------------------------------
# Streamed parse
# ---------------------------------------------------------------------------

def _frames_equal(fr, fr2):
    assert fr.names == fr2.names
    assert fr.nrows == fr2.nrows
    for n in fr.names:
        a, b = fr.vec(n), fr2.vec(n)
        assert a.domain == b.domain, n
        x = np.asarray(a.data)[: fr.nrows]
        y = np.asarray(b.data)[: fr2.nrows]
        np.testing.assert_array_equal(x, y, err_msg=n)


def test_streamed_chunks_match_single_batch(tmp_path, monkeypatch,
                                            mesh8):
    """Forcing many tiny record batches must be BITWISE identical to
    one big batch — chunk boundaries cannot leak into values, codes,
    or domains."""
    p = str(tmp_path / "air.csv")
    D.airlines_csv(p, 3_000, chunk=3_000)
    monkeypatch.delenv("H2O_TPU_ARROW_CSV", raising=False)
    monkeypatch.delenv("H2O_TPU_INGEST_CHUNK_BYTES", raising=False)
    fr = import_file(p)
    assert fr.nrows == 3_000
    monkeypatch.setenv("H2O_TPU_INGEST_CHUNK_BYTES", str(16 << 10))
    fr2 = import_file(p)
    _frames_equal(fr, fr2)


def test_streamed_parse_matches_python_parse(tmp_path, monkeypatch,
                                             mesh8):
    """The streamed arrow reader reproduces the pure-Python parser
    (which DEFINES the parse semantics) on the airlines shape:
    identical names, domains, codes; numerics to float tolerance (the
    two paths parse decimal floats through different routines)."""
    p = str(tmp_path / "air.csv")
    D.airlines_csv(p, 2_000, chunk=2_000)
    monkeypatch.delenv("H2O_TPU_ARROW_CSV", raising=False)
    monkeypatch.setenv("H2O_TPU_INGEST_CHUNK_BYTES", str(64 << 10))
    fr = import_file(p)
    monkeypatch.setenv("H2O_TPU_ARROW_CSV", "0")
    fr2 = import_file(p)
    assert fr.names == fr2.names
    for n in fr.names:
        a, b = fr.vec(n), fr2.vec(n)
        assert a.domain == b.domain, n
        x = np.asarray(a.data)[: fr.nrows]
        y = np.asarray(b.data)[: fr2.nrows]
        if a.is_enum():
            np.testing.assert_array_equal(x, y, err_msg=n)
        else:
            assert np.allclose(x, y, equal_nan=True), n


def test_truncated_csv_fails_loudly(tmp_path, monkeypatch, mesh8):
    """A stream aborting mid-record must fail the parse — both paths —
    never ship a short frame (the chaos drill rehearses the same at
    20k rows). The cut lands two fields into a record (same rule as
    chaos.py _mid_record_cut): a cut at a record boundary or inside
    the last field parses legally as a shorter file and can't test
    this."""
    p = str(tmp_path / "t.csv")
    D.airlines_csv(p, 500, chunk=500)
    with open(p, "rb") as f:
        blob = f.read()
    line_start = blob.rindex(b"\n", 0, int(len(blob) * 0.6)) + 1
    with open(p, "r+b") as f:
        f.truncate(blob.index(b",", line_start) + 1)
    monkeypatch.delenv("H2O_TPU_ARROW_CSV", raising=False)
    with pytest.raises(Exception):
        import_file(p)
    monkeypatch.setenv("H2O_TPU_ARROW_CSV", "0")
    with pytest.raises(ValueError, match="columns"):
        import_file(p)


def test_short_row_fails_loudly(tmp_path, mesh8):
    p = tmp_path / "s.csv"
    p.write_text("a,b,c\n1,2,3\n4,5\n")
    with pytest.raises(ValueError, match="columns"):
        import_file(str(p))


# ---------------------------------------------------------------------------
# Frame.binned
# ---------------------------------------------------------------------------

def test_frame_binned_matches_apply_bins_bitwise(mesh8, monkeypatch):
    fr = _mixed_frame()
    names = ["x1", "x2", "c", "hc"]
    spec = fit_bins(fr, names, n_bins=64)
    # force several column blocks so the block seam is exercised
    monkeypatch.setenv("H2O_TPU_BIN_BLOCK_COLS", "2")
    got = np.asarray(fr.binned(spec))
    import jax.numpy as jnp

    want = np.asarray(apply_bins_jit(
        fr.to_matrix(names), jnp.asarray(spec.edges_matrix()),
        jnp.asarray(np.array(spec.is_enum)), spec.na_bin))
    np.testing.assert_array_equal(got, want)


def test_frame_binned_lru_refreshes_on_hit(mesh8):
    """A,B,A,C with cap 2 must keep A (a hit refreshes recency) —
    FIFO would evict the just-used A and re-pay a full binning pass."""
    fr = _mixed_frame(n=400, seed=7)
    sa = fit_bins(fr, ["x1", "x2", "c"], n_bins=16)
    sb = fit_bins(fr, ["x1", "x2"], n_bins=16)
    sc = fit_bins(fr, ["x1"], n_bins=16)
    a = fr.binned(sa)
    fr.binned(sb)
    assert fr.binned(sa) is a             # hit → A most recent
    fr.binned(sc)                         # evicts B, not A
    assert fr.binned(sa) is a


def test_frame_binned_cache_and_invalidation(mesh8):
    fr = _mixed_frame(n=600, seed=5)
    names = ["x1", "x2", "c"]
    spec = fit_bins(fr, names, n_bins=32)
    a = fr.binned(spec)
    assert fr.binned(spec) is a           # cache hit
    fr["extra"] = fr["x1"] + 1.0          # mutation invalidates
    assert fr.binned(spec) is not a


def test_host_chunks_match_frame_binned(mesh8):
    fr = _mixed_frame(n=700, seed=6)
    names = ["x1", "x2", "c", "hc"]
    spec = fit_bins(fr, names, n_bins=32)
    full = np.asarray(fr.binned(spec))
    chunk_rows = 256
    bufs = bin_frame_host_chunks(fr, spec, chunk_rows)
    padded = fr.vec("x1").padded_len
    cat = np.concatenate(bufs)[:padded]
    np.testing.assert_array_equal(cat, full)
    # rows past the padded length carry the NA bin
    assert (np.concatenate(bufs)[padded:] == spec.na_bin).all()


# ---------------------------------------------------------------------------
# Out-of-core GBM parity
# ---------------------------------------------------------------------------

def _exact_gaussian_frame(n=4096, seed=11):
    """y ∈ {0,1} with an exactly even split: the gaussian prior is
    exactly 0.5, first-round gradients are ±0.5, and every histogram
    partial sum is exactly representable — chunk-boundary f32
    reassociation cannot change a bit."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = np.zeros(n, dtype=np.float32)
    y[rng.permutation(n)[: n // 2]] = 1.0
    cols = {f"f{i}": X[:, i] for i in range(5)}
    cols["y"] = y
    return h2o.Frame.from_arrays(cols)


def _tree_arrays(m):
    import jax

    return [np.asarray(a) for a in jax.tree.flatten(m.trees)[0]]


def test_ooc_matches_resident_bitwise(mesh8, monkeypatch):
    """Streamed (host-pinned, double-buffered device_put) chunks vs
    device-resident chunks: same chunk grid, same adds — every tree
    array and every prediction must be bit-identical."""
    rng = np.random.default_rng(0)
    n = 2048
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.where(X[:, 0] + 0.5 * X[:, 1] +
                 rng.normal(scale=0.5, size=n) > 0, "p", "n")
    cols = {f"f{i}": X[:, i] for i in range(4)}
    cols["y"] = y
    monkeypatch.setenv("H2O_TPU_OOC", "1")
    monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "512")
    monkeypatch.delenv("H2O_TPU_OOC_RESIDENT", raising=False)
    fr = h2o.Frame.from_arrays(dict(cols))
    m_stream = GBM(ntrees=3, max_depth=3, seed=7).train(
        y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC_RESIDENT", "1")
    fr2 = h2o.Frame.from_arrays(dict(cols))
    m_res = GBM(ntrees=3, max_depth=3, seed=7).train(
        y="y", training_frame=fr2)
    for a, b in zip(_tree_arrays(m_stream), _tree_arrays(m_res)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(m_stream.predict_raw(fr),
                                  m_res.predict_raw(fr))


def test_ooc_matches_monolithic_bitwise_exact_sums(mesh8, monkeypatch):
    """Chunk-accumulated vs fused-monolithic on the exact-sum gaussian
    construction: bitwise-equal trees, margins and predictions."""
    fr = _exact_gaussian_frame()
    kw = dict(ntrees=1, max_depth=3, distribution="gaussian", seed=3,
              min_rows=4.0)
    monkeypatch.setenv("H2O_TPU_OOC", "0")
    m_mono = GBM(**kw).train(y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC", "1")
    monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "1024")
    m_ooc = GBM(**kw).train(y="y", training_frame=fr)
    assert float(m_mono.init_score) == float(m_ooc.init_score) == 0.5
    for a, b in zip(_tree_arrays(m_mono), _tree_arrays(m_ooc)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(m_mono.predict_raw(fr),
                                  m_ooc.predict_raw(fr))
    h_m = m_mono.scoring_history[-1]["train_rmse"]
    h_o = m_ooc.scoring_history[-1]["train_rmse"]
    assert h_m == h_o


def test_ooc_close_to_monolithic_multitree(mesh8, monkeypatch):
    """Multi-tree bernoulli: later rounds' gradients are general f32,
    so chunk-boundary reassociation may flip low-order bits — the
    models must still agree to float tolerance."""
    rng = np.random.default_rng(1)
    n = 3072
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.where(X[:, 0] - 0.7 * X[:, 2] +
                 rng.normal(scale=0.4, size=n) > 0, "y", "n")
    cols = {f"f{i}": X[:, i] for i in range(6)}
    cols["y"] = y
    fr = h2o.Frame.from_arrays(cols)
    monkeypatch.setenv("H2O_TPU_OOC", "0")
    m_mono = GBM(ntrees=5, max_depth=4, seed=2).train(
        y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC", "1")
    monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "1024")
    m_ooc = GBM(ntrees=5, max_depth=4, seed=2).train(
        y="y", training_frame=fr)
    p1 = m_mono.predict_raw(fr)
    p2 = m_ooc.predict_raw(fr)
    assert np.allclose(p1, p2, atol=2e-3), np.abs(p1 - p2).max()
    a1 = m_mono.scoring_history[-1]["train_auc"]
    a2 = m_ooc.scoring_history[-1]["train_auc"]
    assert abs(a1 - a2) < 5e-3


def test_ooc_gate_keeps_cadence_and_sampling_in_hbm(mesh8, monkeypatch):
    """score_every and sample_rate<1 are OOC-ineligible even when
    H2O_TPU_OOC=1 forces the mode: a requested scoring cadence must
    never be dropped, and a row-sample draw must never depend on the
    chunk-size knob — both train on the in-HBM path instead."""
    fr = _exact_gaussian_frame(n=1024, seed=12)
    monkeypatch.setenv("H2O_TPU_OOC", "1")
    monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "256")
    kw = dict(max_depth=2, distribution="gaussian", seed=1)
    m = GBM(ntrees=4, score_every=2, **kw).train(
        y="y", training_frame=fr)
    assert len(m.scoring_history) >= 2    # cadence honored
    m1 = GBM(ntrees=3, sample_rate=0.8, **kw).train(
        y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "128")
    m2 = GBM(ntrees=3, sample_rate=0.8, **kw).train(
        y="y", training_frame=fr)
    for a, b in zip(_tree_arrays(m1), _tree_arrays(m2)):
        np.testing.assert_array_equal(a, b)   # chunk knob can't matter
    # col subsampling: fused vs streamed key schedules differ, so it
    # must gate to the in-HBM path — OOC on/off can't change the model
    m3 = GBM(ntrees=3, col_sample_rate_per_tree=0.6, **kw).train(
        y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC", "0")
    m4 = GBM(ntrees=3, col_sample_rate_per_tree=0.6, **kw).train(
        y="y", training_frame=fr)
    for a, b in zip(_tree_arrays(m3), _tree_arrays(m4)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_ooc_1m_row_exact_parity(mesh8, monkeypatch):
    """The tier-1 exact-sum construction at 1M rows with forced small
    chunks — the scale point where the streamed path actually streams
    (≈29 chunks of 36k rows)."""
    fr = _exact_gaussian_frame(n=1_000_000, seed=4)
    kw = dict(ntrees=1, max_depth=4, distribution="gaussian", seed=5)
    monkeypatch.setenv("H2O_TPU_OOC", "0")
    m_mono = GBM(**kw).train(y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC", "1")
    monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "36864")
    m_ooc = GBM(**kw).train(y="y", training_frame=fr)
    for a, b in zip(_tree_arrays(m_mono), _tree_arrays(m_ooc)):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# Satellites
# ---------------------------------------------------------------------------

def test_scorer_cache_lru_eviction(mesh8, monkeypatch):
    from h2o_kubernetes_tpu.models import base as MB

    rng = np.random.default_rng(2)
    n = 256
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = np.where(X[:, 0] > 0, "a", "b")
    cols = {f"f{i}": X[:, i] for i in range(3)}
    cols["y"] = y
    fr = h2o.Frame.from_arrays(cols)
    monkeypatch.delenv("H2O_TPU_OOC", raising=False)
    models = [GBM(ntrees=2, max_depth=2, seed=s).train(
        y="y", training_frame=fr) for s in (1, 2)]
    monkeypatch.setenv("H2O_TPU_SCORER_CACHE_MAX", "1")
    ev0 = MB.scorer_cache_stats()["evictions"]
    out0 = models[0].score_numpy(X)
    models[1].score_numpy(X)              # cap 1 → evicts models[0]
    assert MB.scorer_cache_stats()["evictions"] > ev0
    assert "_scorer_cache" not in models[0].__dict__
    # the evicted model still scores (cache recreated = a normal miss)
    m0 = MB.scorer_cache_stats()["misses"]
    out1 = models[0].score_numpy(X)
    assert MB.scorer_cache_stats()["misses"] > m0
    np.testing.assert_array_equal(out0, out1)


def test_select_rows_device_gather_parity(mesh8, monkeypatch):
    monkeypatch.setenv("H2O_TPU_DEVICE_GATHER_MIN", "0")
    rng = np.random.default_rng(9)
    n = 1000
    t0 = np.datetime64("2024-01-01T00:00:00", "ms")
    fr = h2o.Frame.from_arrays({
        "x": rng.normal(size=n).astype(np.float32),
        "c": np.array(["a", "b", "c"])[rng.integers(0, 3, size=n)],
        "t": t0 + rng.integers(0, 10 ** 9, size=n).astype(
            "timedelta64[ms]"),
    })
    idx = rng.permutation(n)[: 333]       # a CV-fold-like slice
    sub = fr.select_rows(idx)
    assert sub.nrows == 333
    np.testing.assert_array_equal(sub["x"].to_numpy(),
                                  fr["x"].to_numpy()[idx])
    np.testing.assert_array_equal(sub["c"].to_numpy(),
                                  fr["c"].to_numpy()[idx])
    assert sub["c"].domain == fr["c"].domain
    np.testing.assert_array_equal(sub["t"].to_numpy(),
                                  fr["t"].to_numpy()[idx])
    assert sub["t"].kind == "time"
    # negative indices normalize like numpy; out-of-range raises
    one = fr["x"].select_rows(np.array([-1]))
    assert one.to_numpy()[0] == fr["x"].to_numpy()[-1]
    with pytest.raises(IndexError):
        fr["x"].select_rows(np.array([n]))
    # float indices raise like numpy fancy-indexing, never truncate
    with pytest.raises(IndexError, match="integer"):
        fr["x"].select_rows(np.array([0.9, 2.7]))
    # empty selection stays on the host path and yields a 0-row Vec
    assert fr["x"].select_rows(np.array([], dtype=int)).nrows == 0
