"""Frame munging: filters, arithmetic, group_by, merge, sort.

Reference behaviors: h2o-py Frame operators and the Rapids ASTs they
compile to (water/rapids/ast/prims/mungers+operators+math [U3]) —
boolean row slices, elementwise Vec algebra, AstGroup aggregates,
AstMerge inner/left joins. Pandas is the numerical oracle.
"""

import numpy as np
import pandas as pd
import pytest

from h2o_kubernetes_tpu import Frame


@pytest.fixture
def fr(mesh8):
    rng = np.random.default_rng(7)
    n = 101
    return Frame.from_arrays({
        "g": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        "x": rng.normal(size=n).astype(np.float32),
        "y": rng.normal(size=n).astype(np.float32),
    }), n


def test_filter_and_arithmetic(fr):
    fr, n = fr
    x = fr["x"].to_numpy()
    sub = fr[fr["x"] > 0]
    assert sub.nrows == int((x > 0).sum())
    assert np.all(sub["x"].to_numpy() > 0)

    z = fr["x"] * 2.0 + fr["y"]
    np.testing.assert_allclose(
        z.to_numpy(), 2 * x + fr["y"].to_numpy(), rtol=1e-6)
    r = (1.0 - fr["x"]) / 2.0
    np.testing.assert_allclose(r.to_numpy(), (1 - x) / 2, rtol=1e-6)
    np.testing.assert_allclose(fr["x"].abs().to_numpy(), np.abs(x),
                               rtol=1e-6)


def test_filter_na_rows_drop(mesh8):
    fr = Frame.from_arrays({"x": np.array([1.0, np.nan, -1.0, 2.0])})
    out = fr[fr["x"] > 0]
    np.testing.assert_array_equal(out["x"].to_numpy(), [1.0, 2.0])
    out2 = fr[fr["x"].isna()]
    assert out2.nrows == 1


def test_enum_equality_filter(fr):
    fr, n = fr
    sub = fr[fr["g"] == "b"]
    codes = fr["g"].to_numpy()
    b = fr["g"].domain.index("b")
    assert sub.nrows == int((codes == b).sum())
    assert all(sub["g"].to_numpy() == sub["g"].domain.index("b"))

    both = fr[(fr["g"] == "a") | (fr["g"] == "c")]
    assert both.nrows == n - fr[fr["g"] == "b"].nrows


def test_compound_filter(fr):
    fr, n = fr
    x, y = fr["x"].to_numpy(), fr["y"].to_numpy()
    sub = fr[(fr["x"] > 0) & (fr["y"] < 0.5)]
    assert sub.nrows == int(((x > 0) & (y < 0.5)).sum())


def test_group_by_against_pandas(fr):
    fr, n = fr
    out = fr.group_by("g").sum("x").mean("y").count().get_frame()
    pdf = fr.to_pandas()
    exp = pdf.groupby("g").agg(sum_x=("x", "sum"), mean_y=("y", "mean"),
                               nrow=("x", "size")).reset_index()
    got = out.to_pandas().sort_values("g").reset_index(drop=True)
    exp = exp.sort_values("g").reset_index(drop=True)
    np.testing.assert_array_equal(got["g"], exp["g"])
    np.testing.assert_allclose(got["sum_x"], exp["sum_x"], rtol=1e-4)
    np.testing.assert_allclose(got["mean_y"], exp["mean_y"], rtol=1e-4)
    np.testing.assert_array_equal(got["nrow"], exp["nrow"])


def test_group_by_min_max_sd(fr):
    fr, n = fr
    out = fr.group_by(["g"]).min("x").max("x").sd("x").get_frame()
    pdf = fr.to_pandas()
    exp = pdf.groupby("g")["x"].agg(["min", "max", "std"]).reset_index()
    got = out.to_pandas().sort_values("g").reset_index(drop=True)
    np.testing.assert_allclose(got["min_x"], exp["min"], rtol=1e-5)
    np.testing.assert_allclose(got["max_x"], exp["max"], rtol=1e-5)
    np.testing.assert_allclose(got["sd_x"], exp["std"], rtol=1e-4)


def test_group_by_na_group(mesh8):
    fr = Frame.from_arrays({
        "g": np.array(["a", None, "a", None], dtype=object),
        "x": np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)})
    out = fr.group_by("g").sum("x").get_frame()
    assert out.nrows == 2                 # "a" and the NA group
    pdf = out.to_pandas()
    a_sum = float(pdf.loc[pdf["g"] == "a", "sum_x"].iloc[0])
    na_sum = float(pdf.loc[pdf["g"].isna(), "sum_x"].iloc[0])
    assert a_sum == 4.0 and na_sum == 6.0


def test_merge_inner(mesh8):
    left = Frame.from_arrays({
        "k": np.array(["a", "b", "c", "b"]),
        "x": np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)})
    right = Frame.from_arrays({
        "k": np.array(["b", "d", "b"]),
        "y": np.array([10.0, 20.0, 30.0], dtype=np.float32)})
    out = left.merge(right)
    # rows with k=="b" match twice each -> 2*2 rows
    assert out.nrows == 4
    pdf = out.to_pandas()
    assert set(pdf["k"]) == {"b"}
    assert sorted(pdf["y"]) == [10.0, 10.0, 30.0, 30.0]


def test_merge_left(mesh8):
    left = Frame.from_arrays({
        "k": np.array([1, 2, 3], dtype=np.float32),
        "x": np.array([1.0, 2.0, 3.0], dtype=np.float32)})
    right = Frame.from_arrays({
        "k": np.array([2], dtype=np.float32),
        "y": np.array([9.0], dtype=np.float32)})
    out = left.merge(right, all_x=True)
    assert out.nrows == 3
    pdf = out.to_pandas().sort_values("k")
    np.testing.assert_array_equal(np.isnan(pdf["y"]), [True, False, True])


def test_sort(fr):
    fr, n = fr
    out = fr.sort("x")
    assert np.all(np.diff(out["x"].to_numpy()) >= 0)
    out2 = fr.sort("x", ascending=False)
    assert np.all(np.diff(out2["x"].to_numpy()) <= 0)


def test_derived_column_assignment(fr):
    fr, n = fr
    fr["z"] = fr["x"] * fr["x"]
    assert "z" in fr.names
    np.testing.assert_allclose(fr["z"].to_numpy(),
                               fr["x"].to_numpy() ** 2, rtol=1e-6)


def test_enum_arithmetic_rejected(fr):
    fr, n = fr
    with pytest.raises(TypeError):
        fr["g"] * 2
    with pytest.raises(TypeError):
        fr["g"] > 1
    with pytest.raises(TypeError):
        fr["x"] + fr["g"]
    with pytest.raises(TypeError):
        fr["g"].log()


def test_sort_descending_stable_na_last(mesh8):
    fr = Frame.from_arrays({"x": np.array([1.0, np.nan, 3.0, 2.0])})
    out = fr.sort("x", ascending=False)["x"].to_numpy()
    np.testing.assert_array_equal(out[:3], [3.0, 2.0, 1.0])
    assert np.isnan(out[3])


def test_group_by_numeric_key_stays_numeric(mesh8):
    fr = Frame.from_arrays({"k": np.array([2.0, 10.0, 2.0]),
                            "x": np.array([1.0, 2.0, 3.0])})
    out = fr.group_by("k").sum("x").get_frame()
    assert not out["k"].is_enum()
    got = dict(zip(out["k"].to_numpy(), out["sum_x"].to_numpy()))
    assert got[2.0] == 4.0 and got[10.0] == 2.0
