"""Test bootstrap: force an 8-device CPU platform BEFORE jax imports.

Mirrors the reference's test trick (SURVEY.md §4): H2O tests boot a real
multi-JVM cloud on localhost; we boot a real 8-device mesh on CPU so
shard_map/psum semantics are exercised for real — no mocked collectives.
"""

import os
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # env ships JAX_PLATFORMS=axon (TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()


def _xla_flags_supported(candidate: str) -> bool:
    """XLA treats unknown XLA_FLAGS as FATAL (parse_flags_from_env
    aborts the process at first backend init), so a flag the installed
    jaxlib doesn't know would kill every test in the suite before one
    runs — probe support in a throwaway interpreter instead.

    The answer depends only on the installed jaxlib, so it is cached
    on disk per jaxlib version: only the first pytest run on a box
    pays the subprocess jax init."""
    import hashlib
    import tempfile

    try:
        import jaxlib

        ver = getattr(jaxlib, "__version__", "unknown")
    except ImportError:
        ver = "nojaxlib"
    tag = hashlib.sha1(f"{ver}|{candidate}".encode()).hexdigest()[:16]
    cache = os.path.join(tempfile.gettempdir(),
                         f".h2o_tpu_xla_flag_probe_{tag}")
    try:
        with open(cache) as f:
            return f.read().strip() == "1"
    except OSError:
        pass
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=candidate)
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, capture_output=True, timeout=300)
    except Exception:
        return False            # transient (timeout/spawn) — don't cache
    ok = r.returncode == 0
    # cache "0" ONLY for the definitive unknown-flag abort; any other
    # nonzero exit (OOM-killed probe, transient breakage) must retry
    # next run, or supported flags would be dropped forever silently
    if ok or b"Unknown flags" in r.stderr:
        try:
            with open(cache, "w") as f:
                f.write("1" if ok else "0")
        except OSError:
            pass
    return ok


if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
    # Root cause of round-1's roaming full-suite SIGABRT: XLA:CPU's
    # collective rendezvous TERMINATES the process ("Termination timeout
    # for ... exceeded. Exiting to ensure a consistent program state")
    # when the 8 shard threads of a psum don't all arrive in time — on a
    # 1-core box under load, thread starvation trips it nondeterministically
    # ~2h of cumulative scheduling into a run. Raise the deadline far past
    # any real scheduling delay; a true deadlock still fails via the
    # suite-level timeout instead of a silent abort.
    # These flags only exist in newer XLA builds — adding them blindly
    # is itself a fatal abort on older jaxlibs (the round-5 seed state:
    # DOTS_PASSED=0 because every pytest process died in make_cpu_client).
    _collective = (" --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
                   " --xla_cpu_collective_call_terminate_timeout_seconds=7200"
                   " --xla_cpu_collective_timeout_seconds=7200")
    if _xla_flags_supported(flags + _collective):
        flags += _collective
os.environ["XLA_FLAGS"] = flags

# sitecustomize may import jax at interpreter start (latching
# jax_platforms=axon from the env); backends are still uninitialized at
# conftest time, so overriding the live config takes effect.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_runtest_logreport(report):
    """Per-test wall-clock lines (opt-in via H2O_TPU_TEST_TIMINGS):
    tools/run_tests.py turns these into a "slowest 5 tests" digest when
    a module TIMES OUT — pytest's own --durations only prints at
    session end, which a killed module never reaches (the known
    XLA:CPU rendezvous stalls present exactly like that)."""
    if report.when == "call" and os.environ.get("H2O_TPU_TEST_TIMINGS"):
        print(f"[time] {report.duration:.2f}s {report.nodeid}",
              flush=True)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches():
    """Free compiled executables after every test module.

    Root cause of round-1's roaming full-suite SIGABRT: each train()
    call jit-compiles fresh executables whose memory mappings are never
    released (~600-1500 maps/test), and the process walks into the
    kernel's vm.max_map_count (65530) around test ~120 — mmap then
    fails inside eager dispatch and XLA aborts without a message.
    Clearing per module caps the accumulation at single-module scale.
    """
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="session")
def mesh8():
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh

    mesh = make_mesh()
    set_global_mesh(mesh)
    return mesh
