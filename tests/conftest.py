"""Test bootstrap: force an 8-device CPU platform BEFORE jax imports.

Mirrors the reference's test trick (SURVEY.md §4): H2O tests boot a real
multi-JVM cloud on localhost; we boot a real 8-device mesh on CPU so
shard_map/psum semantics are exercised for real — no mocked collectives.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # env ships JAX_PLATFORMS=axon (TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# sitecustomize may import jax at interpreter start (latching
# jax_platforms=axon from the env); backends are still uninitialized at
# conftest time, so overriding the live config takes effect.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh

    mesh = make_mesh()
    set_global_mesh(mesh)
    return mesh
