"""Test bootstrap: force an 8-device CPU platform BEFORE jax imports.

Mirrors the reference's test trick (SURVEY.md §4): H2O tests boot a real
multi-JVM cloud on localhost; we boot a real 8-device mesh on CPU so
shard_map/psum semantics are exercised for real — no mocked collectives.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # env ships JAX_PLATFORMS=axon (TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
    # Root cause of round-1's roaming full-suite SIGABRT: XLA:CPU's
    # collective rendezvous TERMINATES the process ("Termination timeout
    # for ... exceeded. Exiting to ensure a consistent program state")
    # when the 8 shard threads of a psum don't all arrive in time — on a
    # 1-core box under load, thread starvation trips it nondeterministically
    # ~2h of cumulative scheduling into a run. Raise the deadline far past
    # any real scheduling delay; a true deadlock still fails via the
    # suite-level timeout instead of a silent abort.
    flags = (flags +
             " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
             " --xla_cpu_collective_call_terminate_timeout_seconds=7200"
             " --xla_cpu_collective_timeout_seconds=7200").strip()
os.environ["XLA_FLAGS"] = flags

# sitecustomize may import jax at interpreter start (latching
# jax_platforms=axon from the env); backends are still uninitialized at
# conftest time, so overriding the live config takes effect.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches():
    """Free compiled executables after every test module.

    Root cause of round-1's roaming full-suite SIGABRT: each train()
    call jit-compiles fresh executables whose memory mappings are never
    released (~600-1500 maps/test), and the process walks into the
    kernel's vm.max_map_count (65530) around test ~120 — mmap then
    fails inside eager dispatch and XLA aborts without a message.
    Clearing per module caps the accumulation at single-module scale.
    """
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="session")
def mesh8():
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh

    mesh = make_mesh()
    set_global_mesh(mesh)
    return mesh
