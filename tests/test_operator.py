"""Operator scorer fleets (ISSUE 6 tentpole): the model registry's
MOJO-v2 round trip must be bitwise (the replica scorer descends the
SAME flat arrays with the SAME flat_margin executable), format-v1
artifacts must reject, the warm-up contract (pow2 ladder pre-traced →
zero misses on first traffic) must pin, and the reconcile loop must
converge on replica death, spec resize, and artifact change — driven
here with fake replicas (pure orchestration; the real-subprocess legs
live in tools/chaos.py's rolling-update and replica-kill drills)."""

import io
import json
import socket
import threading
import urllib.error
import urllib.request
import zipfile

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest
from h2o_kubernetes_tpu.models import GBM, GLM
from h2o_kubernetes_tpu.models.base import Model, scorer_cache_stats
from h2o_kubernetes_tpu.mojo import read_mojo_parts
from h2o_kubernetes_tpu.operator import (DurablePoolStore,
                                         FlatTreeScorer, ModelRegistry,
                                         PoolStore, Reconciler,
                                         ScorerPoolSpec,
                                         StaleGenerationError,
                                         load_artifact)
from h2o_kubernetes_tpu.operator.autoscale import desired_replicas
from h2o_kubernetes_tpu.operator.reconcile import (CORDONED, DEAD,
                                                   DRAINING, LOADING,
                                                   READY, STARTING)

pytestmark = pytest.mark.chaos

from test_flat_scorer import _rich_frame  # noqa: E402 — the shared
# parity fixture (NAs, high-card enums, weights, offset); bare module
# import because tests/ is pytest-inserted, not a package


def _gbm(fr, seed=1, **kw):
    kw.setdefault("ntrees", 5)
    kw.setdefault("max_depth", 3)
    kw.setdefault("nbins", 64)
    return GBM(seed=seed, **kw).train(y="y", training_frame=fr)


# ---------------------------------------------------------------------------
# Registry: artifact round trip
# ---------------------------------------------------------------------------


def test_registry_roundtrip_bitwise(mesh8):
    """MOJO-v2 bytes written by the registry load bitwise-identically
    on a scorer replica: flat arrays equal to the source model's
    flattening, and score_numpy output bitwise-equal — NAs, high-card
    grouped enums and all (the test_flat_scorer parity frame)."""
    fr = _rich_frame(n=600, seed=13)
    m = _gbm(fr)
    reg = ModelRegistry("mem://test_roundtrip")
    v = reg.publish(m, "scorer")
    blob = reg.fetch("scorer", v)
    meta, arrays, _ = read_mojo_parts(io.BytesIO(blob))
    flat = m._flat()
    for f in ("split_feat", "thresh", "left", "na_left", "value"):
        assert np.array_equal(arrays[f"flat_{f}"],
                              np.asarray(getattr(flat, f))), f
    sc = load_artifact(blob)
    assert isinstance(sc, FlatTreeScorer) and sc._serving_jit
    X = np.asarray(m._design_matrix(fr))[: fr.nrows]
    assert np.array_equal(sc.score_numpy(X), m.score_numpy(X))
    # schema travels: feature names/domains drive the REST row parser
    assert sc.feature_names == m.feature_names
    assert sc.feature_domains == m.feature_domains
    assert sc.response_domain == m.response_domain


def test_registry_versions_and_digest(mesh8):
    fr = _rich_frame(n=400, seed=3)
    reg = ModelRegistry("mem://test_versions")
    v1 = reg.publish(_gbm(fr, seed=1), "scorer")
    v2 = reg.publish(_gbm(fr, seed=2, ntrees=7), "scorer")
    assert (v1, v2) == (1, 2)
    assert reg.latest("scorer") == 2
    assert reg.fetch("scorer", 1) != reg.fetch("scorer", 2)
    with pytest.raises(KeyError):
        reg.latest("nope")
    # corrupted blob must refuse to serve
    from h2o_kubernetes_tpu import persist

    path = reg.artifact_path("scorer", 2)
    persist.write_bytes(path, b"garbage" + reg.fetch("scorer", 1))
    with pytest.raises(IOError, match="digest"):
        reg.fetch("scorer", 2)


def test_registry_rejects_v1_artifact(mesh8):
    """A format-v1 artifact (heap trees + edges, pre-flattening) has
    no serving arrays — the registry load must reject it cleanly."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("model.json", json.dumps(
            {"format": "h2o_kubernetes_tpu/mojo/1", "algo": "gbm"}))
        nz = io.BytesIO()
        np.savez_compressed(nz)
        z.writestr("arrays.npz", nz.getvalue())
    with pytest.raises(ValueError, match="format-v1"):
        load_artifact(buf.getvalue())
    # non-zip garbage: loud, not a crash deeper in
    with pytest.raises(Exception):
        load_artifact(b"not a zip at all")


def test_flat_scorer_pickle_roundtrip(tmp_path, mesh8):
    """A registry scorer must survive save_model/load_model: the base
    __getstate__ drops _flat_trees assuming a lazy rebuild from heap
    trees, which a FlatTreeScorer does not have — it pickles its
    artifact parts instead and rebuilds from them."""
    import pickle

    from h2o_kubernetes_tpu.persist import load_model, save_model

    fr = _rich_frame(n=400, seed=19)
    m = _gbm(fr, ntrees=4)
    reg = ModelRegistry("mem://test_pickle")
    sc = load_artifact(reg.fetch("scorer", reg.publish(m, "scorer")))
    X = np.asarray(m._design_matrix(fr))[: fr.nrows]
    want = sc.score_numpy(X)
    sc2 = pickle.loads(pickle.dumps(sc))
    assert np.array_equal(sc2.score_numpy(X), want)
    p = str(tmp_path / "sc.model")
    save_model(sc, p)
    sc3 = load_model(p)
    assert np.array_equal(sc3.score_numpy(X), want)


def test_registry_rejects_nontree(mesh8):
    rng = np.random.default_rng(0)
    x = rng.normal(size=300).astype(np.float32)
    y = np.where(x > 0, "p", "n")
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    glm = GLM(family="binomial").train(y="y", training_frame=fr)
    reg = ModelRegistry("mem://test_nontree")
    with pytest.raises(ValueError, match="scorer pool"):
        reg.publish(glm, "scorer")


# ---------------------------------------------------------------------------
# Warm-up contract
# ---------------------------------------------------------------------------


def test_warm_up_pow2_ladder_zero_misses(mesh8):
    """warm_up traces the FULL pow2 ladder up to the largest bucket;
    afterwards any batch size in range adds only hits — the
    freshly-provisioned-replica acceptance (warm_cache_misses=0 on
    the first scoring request after readyz flips)."""
    fr = _rich_frame(n=500, seed=21)
    m = _gbm(fr)
    reg = ModelRegistry("mem://test_warm")
    sc = load_artifact(reg.fetch("scorer", reg.publish(m, "scorer")))
    assert sc.warm_up([600]) == [128, 256, 512, 1024]
    X = np.asarray(m._design_matrix(fr))[: fr.nrows]
    s0 = scorer_cache_stats()
    for n in (1, 77, 128, 200, 513, 1024):
        sc.score_numpy(X[np.arange(n) % fr.nrows])
    s1 = scorer_cache_stats()
    assert s1["misses"] == s0["misses"], \
        "a warmed replica paid a trace on in-range traffic"
    assert s1["hits"] == s0["hits"] + 6


def test_warm_up_validation(mesh8):
    m = Model.__new__(Model)        # _serving_jit is False on the base
    with pytest.raises(ValueError, match="no jitted serving scorer"):
        m.warm_up([128])
    fr = _rich_frame(n=300, seed=5)
    g = _gbm(fr, ntrees=3)
    with pytest.raises(ValueError, match="bucket"):
        g.warm_up(["nope"])
    with pytest.raises(ValueError, match="bucket"):
        g.warm_up([0])
    # a JSON string would iterate as DIGITS and silently warm the
    # wrong ladder — must reject, not misinterpret
    with pytest.raises(ValueError, match="string"):
        g.warm_up("512")


# ---------------------------------------------------------------------------
# Reconciler orchestration (fake replicas — subprocess legs in chaos.py)
# ---------------------------------------------------------------------------


class FakeReplica:
    """Scripted in-process stand-in for ScorerReplica: healthy one
    tick after spawn, loaded+ready one tick after the push, dies on
    terminate/kill. Lets the reconcile policy be tested in
    milliseconds."""

    def __init__(self, rid, version, spec):
        self.rid = rid
        self.version = int(version)
        self.model_key = spec.model_key
        self.artifact = spec.artifact
        self.warm_buckets = None if spec.warm_buckets is None \
            else tuple(spec.warm_buckets)
        self.port = 0
        self.state = "PENDING"
        self.created_at = 0.0
        self.cordoned_at = 0.0
        self.drain_at = 0.0
        self._alive = False
        self._loaded = False
        self._load_done = False
        self.stats_payload = None

    @property
    def url(self):
        return f"fake://{self.rid}"

    def spawn(self):
        import time

        self._alive = True
        self.state = STARTING
        self.created_at = time.monotonic()

    def alive(self):
        return self._alive

    def pid(self):
        return None

    def mark_dead(self):
        self.state = DEAD

    def healthz_ok(self):
        return self._alive

    def readyz_ok(self):
        return self._alive and self._loaded

    def stats(self):
        return self.stats_payload

    def loaded_version(self):
        return self.version if self._loaded else None

    def start_load(self, registry):
        self.state = LOADING
        self._loaded = True
        self._load_done = True

    def load_finished(self):
        return self._load_done

    def load_error(self):
        return None

    def cordon(self):
        import time

        self.state = CORDONED
        self.cordoned_at = time.monotonic()

    def terminate(self):
        import time

        self.state = DRAINING
        self.drain_at = time.monotonic()
        self._alive = False           # fake drains instantly

    def kill(self):
        self._alive = False


def _fake_pool(replicas=2, version=1, **spec_kw):
    store = PoolStore()
    spec = ScorerPoolSpec(name="p", artifact="a", version=version,
                          model_key="m", replicas=replicas, **spec_kw)
    store.apply(spec)
    rec = Reconciler(store, registry=None, pool="p",
                     replica_factory=FakeReplica)
    return store, rec


def _settle(rec, passes=30):
    for _ in range(passes):
        rec.reconcile_once()
        if rec.converged():
            return True
    return rec.converged()


def test_reconciler_converges_and_replaces_dead(monkeypatch, mesh8):
    monkeypatch.setenv("H2O_TPU_POOL_DEREGISTER_GRACE", "0")
    store, rec = _fake_pool(replicas=2)
    assert _settle(rec)
    assert [r.state for r in rec.replicas] == [READY, READY]
    # replica death (the SIGKILL drill's orchestration half)
    rec.replicas[0]._alive = False
    assert not rec.converged()
    assert _settle(rec)
    kinds = [e["kind"] for e in store.events("p")]
    died = kinds.index("replica_died")
    assert "replica_start" in kinds[died:]
    assert "replica_ready" in kinds[died:]


def test_reconciler_resize(monkeypatch, mesh8):
    monkeypatch.setenv("H2O_TPU_POOL_DEREGISTER_GRACE", "0")
    store, rec = _fake_pool(replicas=1, max_replicas=8)
    assert _settle(rec)
    store.apply_update("p", replicas=3)
    assert _settle(rec)
    assert sum(1 for r in rec.replicas if r.state == READY) == 3
    store.apply_update("p", replicas=1)
    assert _settle(rec)
    assert sum(1 for r in rec.replicas if r.state == READY) == 1
    # scale-down retired via cordon (never a hard kill of READY)
    kinds = [e["kind"] for e in store.events("p")]
    assert "replica_cordon" in kinds


def test_reconciler_rolling_update_surge_one(monkeypatch, mesh8):
    """Version bump rolls surge-one: capacity never exceeds
    replicas+1, ready count never dips below replicas once converged,
    and the pool ends with every replica on v2."""
    monkeypatch.setenv("H2O_TPU_POOL_DEREGISTER_GRACE", "0")
    store, rec = _fake_pool(replicas=2)
    assert _settle(rec)
    store.apply_update("p", version=2)
    min_ready, max_capacity = 99, 0
    for _ in range(40):
        rec.reconcile_once()
        live = [r for r in rec.replicas if r.state != DEAD]
        ready = [r for r in live if r.state == READY and r.alive()]
        capacity = [r for r in live
                    if r.state in (STARTING, LOADING, READY)]
        min_ready = min(min_ready, len(ready))
        max_capacity = max(max_capacity, len(capacity))
        if rec.converged():
            break
    assert rec.converged()
    assert min_ready >= 2, "rolling update dropped serving capacity"
    assert max_capacity <= 3, "surge exceeded one extra replica"
    assert all(r.version == 2 for r in rec.replicas)
    kinds = [e["kind"] for e in store.events("p")]
    # old replicas retire ONLY after a new-version READY exists
    assert kinds.index("replica_cordon") > kinds.index("replica_ready")


def test_reconciler_startup_timeout_replaces(monkeypatch, mesh8):
    monkeypatch.setenv("H2O_TPU_POOL_STARTUP_DEADLINE", "1")

    class NeverHealthy(FakeReplica):
        def healthz_ok(self):
            return False

    store = PoolStore()
    store.apply(ScorerPoolSpec(name="p", artifact="a", version=1,
                               model_key="m", replicas=1))
    made = []

    def factory(rid, version, spec):
        r = (NeverHealthy if len(made) == 0 else FakeReplica)(
            rid, version, spec)
        made.append(r)
        return r

    rec = Reconciler(store, registry=None, pool="p",
                     replica_factory=factory)
    rec.reconcile_once()            # spawns the wedged one
    import time

    time.sleep(1.1)                 # past the 1s startup deadline
    assert _settle(rec)
    kinds = [e["kind"] for e in store.events("p")]
    assert "replica_startup_timeout" in kinds
    assert made[0].state == DEAD and len(made) == 2


# ---------------------------------------------------------------------------
# Autoscale signal
# ---------------------------------------------------------------------------


def _stats(depth=0, shed=0, d504=0, requests=0):
    return {"batcher": {"queue_depth": depth, "shed": shed,
                        "requests": requests},
            "counters": {"deadline_504": d504}}


def test_autoscale_signal(mesh8):
    spec = ScorerPoolSpec(name="p", artifact="a", version=1,
                          model_key="m", replicas=2, min_replicas=1,
                          max_replicas=4)
    # queue pressure scales up
    n, why, tot = desired_replicas(spec, [_stats(depth=10),
                                          _stats(depth=8)])
    assert n == 3 and "queue depth" in why
    # shed delta scales up (cumulative counters -> rate via prev)
    prev = desired_replicas(spec, [_stats(shed=5)])[2]
    n, why, _ = desired_replicas(spec, [_stats(shed=7)], prev)
    assert n == 3 and "shed" in why
    # deadline 504 delta scales up
    prev = desired_replicas(spec, [_stats(d504=1)])[2]
    n, why, _ = desired_replicas(spec, [_stats(d504=3)], prev)
    assert n == 3 and "deadline" in why
    # clamped at max_replicas
    spec4 = ScorerPoolSpec(name="p", artifact="a", version=1,
                           model_key="m", replicas=4, max_replicas=4)
    assert desired_replicas(spec4, [_stats(depth=99)])[0] == 4
    # idle pool scales down (zero depth, zero deltas)
    prev = desired_replicas(spec, [_stats(requests=100)])[2]
    n, why, _ = desired_replicas(spec, [_stats(requests=100)], prev)
    assert n == 1 and "idle" in why
    # live traffic holds
    prev = desired_replicas(spec, [_stats(requests=100)])[2]
    n, _, _ = desired_replicas(spec, [_stats(requests=150)], prev)
    assert n == 2
    # counter RESET (replica restart / rolling update zeroed the
    # cumulative counters) must HOLD, not read as idleness
    prev = desired_replicas(spec, [_stats(requests=1000)])[2]
    n, why, _ = desired_replicas(spec, [_stats(requests=50)], prev)
    assert n == 2 and "reset" in why
    # no samples: hold (pool still converging)
    assert desired_replicas(spec, [])[0] == 2
    # floor respected
    spec1 = ScorerPoolSpec(name="p", artifact="a", version=1,
                           model_key="m", replicas=1, min_replicas=1)
    prev = desired_replicas(spec1, [_stats()])[2]
    assert desired_replicas(spec1, [_stats()], prev)[0] == 1


# ---------------------------------------------------------------------------
# REST surface: /3/ModelRegistry/load, readiness gate, /3/Stats, cordon
# ---------------------------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def pool_server(mesh8):
    rest.install_pool_replica_gate()
    # counters are process-global: earlier modules in a monolithic
    # pytest run may have admitted scoring on a non-SERVING node —
    # zero them so the ==0 assertions below measure THIS fixture's span
    rest.STATS["scored_while_unready"] = 0
    rest.STATS["deadline_504"] = 0
    port = _free_port()
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.READINESS_GATES.clear()
    rest.REGISTRY_MODELS.clear()
    rest.MODELS.clear()
    from h2o_kubernetes_tpu.runtime import lifecycle

    lifecycle.uncordon()


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_registry_route_gate_and_warm_contract(pool_server):
    """The full replica handshake in-process: gated unready -> push ->
    warmed ready -> first scoring request with warm_cache_misses=0 and
    scored_while_unready=0 (the two drill acceptance counters)."""
    base = pool_server
    code, out = _get(base, "/readyz")
    assert code == 503
    assert any("model-registry" in r for r in out["reasons"])

    fr = _rich_frame(n=400, seed=31)
    m = _gbm(fr, ntrees=4)
    reg = ModelRegistry("mem://test_route")
    v = reg.publish(m, "scorer")
    out = reg.push(base, "scorer", v, "pm", warm_buckets=[128])
    assert out["warmed_buckets"] == [128]
    assert _get(base, "/readyz")[0] == 200
    code, out = _get(base, "/3/ModelRegistry")
    assert code == 200 and out["models"]["pm"]["version"] == v

    # first scoring request after readyz flips: zero warm misses
    rows = [{n: (0.5 if m.feature_domains.get(n) is None else "L1")
             for n in m.feature_names} for _ in range(8)]
    code, out = _post(base, "/3/Predictions/models/pm", {"rows": rows})
    assert code == 200 and len(out["predict"]) == 8
    code, st = _get(base, "/3/Stats")
    assert code == 200
    assert st["registry"]["pm"]["warm_cache_misses"] == 0
    assert st["counters"]["scored_while_unready"] == 0

    # the standard mojo-download verb must work on a registry scorer
    # (no heap trees — it serves its kept artifact parts) and the
    # downloaded artifact must load back into an identical scorer
    req = urllib.request.Request(base + "/3/Models/pm/mojo")
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.status == 200
        blob = r.read()
    sc2 = load_artifact(blob)
    X = np.asarray(m._design_matrix(fr))[: fr.nrows]
    assert np.array_equal(sc2.score_numpy(X), m.score_numpy(X))
    # and a loaded scorer can be re-published (replica promotion)
    assert reg.publish(rest.MODELS["pm"], "promoted") == 1


def test_registry_push_env_default_buckets(pool_server, monkeypatch):
    """A spec without pinned warm_buckets defers to the REPLICA's
    H2O_TPU_POOL_WARM_BUCKETS — push omits the field, the route's
    warm_up(None) resolves the env knob."""
    monkeypatch.setenv("H2O_TPU_POOL_WARM_BUCKETS", "64, 256")
    base = pool_server
    fr = _rich_frame(n=300, seed=41)
    reg = ModelRegistry("mem://test_envbuckets")
    v = reg.publish(_gbm(fr, ntrees=3), "scorer")
    out = reg.push(base, "scorer", v, "pm")     # warm_buckets=None
    assert out["warmed_buckets"] == [128, 256]  # full pow2 ladder
    assert _get(base, "/readyz")[0] == 200


def test_registry_route_rejections(pool_server):
    base = pool_server
    assert _post(base, "/3/ModelRegistry/load", {})[0] == 400
    assert _post(base, "/3/ModelRegistry/load",
                 {"model_id": "x"})[0] == 400
    assert _post(base, "/3/ModelRegistry/load",
                 {"model_id": "x", "artifact_b64": "!!!"})[0] == 400
    assert _post(base, "/3/ModelRegistry/load",
                 {"model_id": "x", "path": "mem://nope/a.mojo"}
                 )[0] == 404
    # v1 artifact inline -> 400 with the re-export message
    import base64

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("model.json", json.dumps(
            {"format": "h2o_kubernetes_tpu/mojo/1", "algo": "gbm"}))
        nz = io.BytesIO()
        np.savez_compressed(nz)
        z.writestr("arrays.npz", nz.getvalue())
    code, out = _post(base, "/3/ModelRegistry/load", {
        "model_id": "x",
        "artifact_b64": base64.b64encode(buf.getvalue()).decode()})
    assert code == 400 and "format-v1" in out["msg"]
    # digest mismatch -> 409
    fr = _rich_frame(n=300, seed=7)
    reg = ModelRegistry("mem://test_rej")
    v = reg.publish(_gbm(fr, ntrees=3), "scorer")
    code, out = _post(base, "/3/ModelRegistry/load", {
        "model_id": "x", "path": reg.artifact_path("scorer", v),
        "sha256": "0" * 64})
    assert code == 409
    # nothing published: the gate still holds readiness down
    assert _get(base, "/readyz")[0] == 503


def test_stats_route_exposes_counters(pool_server):
    """The satellite fix: scorer_cache_stats() and breaker/shed
    counters were process-local — /3/Stats is their REST surface."""
    code, st = _get(pool_server, "/3/Stats")
    assert code == 200
    for k in ("hits", "misses", "models", "evictions"):
        assert k in st["scorer_cache"]
    for k in ("requests", "batches", "shed", "queue_depth"):
        assert k in st["batcher"]
    assert st["breaker"]["state"] == "closed"
    assert "deadline_504" in st["counters"]
    assert st["ready"] is False          # gate installed, nothing loaded


def test_cordon_flips_readyz_not_serving(pool_server):
    """Cordon = endpoint removal: readyz 503 while healthz stays 200
    AND scoring still serves (the straggler window of a rolling
    update); uncordon restores readiness."""
    base = pool_server
    fr = _rich_frame(n=300, seed=9)
    m = _gbm(fr, ntrees=3)
    reg = ModelRegistry("mem://test_cordon")
    reg.push(base, "scorer", reg.publish(m, "scorer"), "pm",
             warm_buckets=[128])
    assert _get(base, "/readyz")[0] == 200
    assert _post(base, "/3/Cordon", {"reason": "test"})[0] == 200
    code, out = _get(base, "/readyz")
    assert code == 503 and any("cordon" in r for r in out["reasons"])
    assert _get(base, "/healthz")[0] == 200
    rows = [{n: (0.1 if m.feature_domains.get(n) is None else "L2")
             for n in m.feature_names}]
    code, _ = _post(base, "/3/Predictions/models/pm", {"rows": rows})
    assert code == 200, "cordoned replica refused a straggler"
    _, st = _get(base, "/3/Stats")
    assert st["counters"]["scored_while_unready"] == 0
    assert _post(base, "/3/Uncordon", {})[0] == 200
    assert _get(base, "/readyz")[0] == 200


# ---------------------------------------------------------------------------
# Durable store (ISSUE 9 tentpole): restart round-trip + fencing
# ---------------------------------------------------------------------------


def test_durable_store_restart_roundtrip(tmp_path, mesh8):
    """Specs, status, and events written by one operator process are
    read back intact by a fresh process (fresh store object over the
    same root) — the control-plane-survives-death acceptance."""
    root = str(tmp_path / "store")
    a = DurablePoolStore(root)
    spec = ScorerPoolSpec(name="p", artifact="a", version=3,
                          model_key="m", replicas=2,
                          warm_buckets=(128,),
                          extra_artifacts=(("a2", 1, "m2"),),
                          env={"K": "V"})
    gen = a.apply(spec)
    gen = a.apply_update("p", replicas=3)
    a.set_status("p", {"converged": False, "ready": 1}, fence=gen)
    a.record_event("p", "replica_start", "p-1 v3")
    a.record_event("p", "replica_ready", "p-1 v3")

    b = DurablePoolStore(root)          # the restarted operator
    spec_b, gen_b = b.get("p")
    assert gen_b == gen == 2
    assert spec_b == ScorerPoolSpec(name="p", artifact="a", version=3,
                                    model_key="m", replicas=3,
                                    warm_buckets=(128,),
                                    extra_artifacts=(("a2", 1, "m2"),),
                                    env={"K": "V"})
    assert b.get_status("p") == {"converged": False, "ready": 1}
    assert [e["kind"] for e in b.events("p")] == \
        ["replica_start", "replica_ready"]
    # deletes persist too
    b.delete("p")
    assert DurablePoolStore(root).pools() == []


def test_durable_store_stale_generation_rejected(tmp_path, mesh8):
    """The fencing acceptance: a controller still holding an old
    generation cannot clobber newer spec or status."""
    store = DurablePoolStore(str(tmp_path / "store"))
    spec = ScorerPoolSpec(name="p", artifact="a", version=1,
                          model_key="m")
    g1 = store.apply(spec)
    g2 = store.apply_update("p", replicas=2)
    assert g2 == g1 + 1
    with pytest.raises(StaleGenerationError):
        store.apply(spec, fence=g1)
    with pytest.raises(StaleGenerationError):
        store.apply_update("p", fence=g1, replicas=9)
    with pytest.raises(StaleGenerationError):
        store.set_status("p", {"x": 1}, fence=g1)
    # the stale writes did NOT land
    assert store.get("p")[0].replicas == 2
    assert store.get_status("p") == {}
    # unfenced + correctly-fenced writes still work
    store.set_status("p", {"x": 2}, fence=g2)
    assert store.get_status("p") == {"x": 2}
    assert store.apply_update("p", replicas=1) == g2 + 1


def test_durable_store_cross_instance_visibility(tmp_path, mesh8):
    """Two store instances over one root (the drill parent + the
    operator child): a spec applied through one is observed by the
    other on its next read, and status flows the other way — the
    store file is the API-server wire."""
    root = str(tmp_path / "store")
    client = DurablePoolStore(root)
    client.apply(ScorerPoolSpec(name="p", artifact="a", version=1,
                                model_key="m"))
    operator = DurablePoolStore(root)
    assert operator.get("p")[0].version == 1
    client.apply_update("p", version=2)          # client bumps
    spec, gen = operator.get("p")                # operator observes
    assert spec.version == 2 and gen == 2
    operator.set_status("p", {"ready": 1}, fence=gen)
    operator.record_event("p", "replica_ready", "p-1")
    assert client.get_status("p") == {"ready": 1}  # client observes
    assert [e["kind"] for e in client.events("p")] == ["replica_ready"]


def test_durable_store_event_ring_bounded(tmp_path, mesh8):
    store = DurablePoolStore(str(tmp_path / "store"))
    store.apply(ScorerPoolSpec(name="p", artifact="a", version=1,
                               model_key="m"))
    for i in range(300):
        store.record_event("p", "k", str(i))
    reloaded = DurablePoolStore(str(tmp_path / "store"))
    evs = reloaded.events("p")
    assert len(evs) == 256 and evs[-1]["msg"] == "299"


def test_atomic_write_and_listing(tmp_path, mesh8):
    """persist.write_bytes_atomic: replace-in-place, read-back
    verified, and no temp droppings; list_names sees only files."""
    from h2o_kubernetes_tpu import persist

    p = str(tmp_path / "d" / "f.json")
    persist.write_bytes_atomic(p, b"v1")
    persist.write_bytes_atomic(p, b"v2")
    assert persist.read_bytes(p) == b"v2"
    import os

    assert sorted(os.listdir(tmp_path / "d")) == ["f.json"]
    (tmp_path / "d" / "sub").mkdir()
    assert persist.list_names(str(tmp_path / "d")) == ["f.json"]
    assert persist.list_names(str(tmp_path / "nope")) == []


# ---------------------------------------------------------------------------
# Controller lease + fenced routing publication (ISSUE 16: the HA
# control plane; real-subprocess leg in tools/chaos.py router-ha-kill)
# ---------------------------------------------------------------------------


def test_lease_lifecycle_exclusive_renew_takeover(tmp_path, mesh8):
    import time

    root = str(tmp_path / "store")
    a = DurablePoolStore(root)
    b = DurablePoolStore(root)           # a second operator replica
    # first acquire wins epoch 1; the standby polls None
    assert a.acquire_lease("p", "op-a", ttl=30.0) == 1
    assert b.acquire_lease("p", "op-b", ttl=30.0) is None
    # the holder re-acquiring / renewing does NOT bump the epoch —
    # the fence must only move on ownership CHANGE
    assert a.acquire_lease("p", "op-a", ttl=30.0) == 1
    assert a.renew_lease("p", "op-a", 1) is True
    # a non-holder's renew is strictly refused
    assert b.renew_lease("p", "op-b", 1) is False
    doc = b.get_lease("p")
    assert doc["holder"] == "op-a" and doc["epoch"] == 1
    # expiry: the holder misses its heartbeat window, the standby's
    # claim succeeds WITH an epoch bump, and the old holder's next
    # heartbeat fails (it must stop reconciling immediately)
    assert a.acquire_lease("p", "op-a", ttl=0.05) == 1
    time.sleep(0.08)
    assert b.acquire_lease("p", "op-b", ttl=30.0) == 2
    assert a.renew_lease("p", "op-a", 1) is False
    # voluntary release keeps the epoch monotonic: the released
    # marker still carries epoch 2 so the next claim bumps to 3 — a
    # long-deposed holder can never slide back under an old fence
    b.release_lease("p", "op-b")
    doc = a.get_lease("p")
    assert doc.get("released") and doc["epoch"] == 2
    assert a.acquire_lease("p", "op-a", ttl=30.0) == 3


def test_publish_routing_generation_and_epoch_fence(tmp_path, mesh8):
    import time

    store = DurablePoolStore(str(tmp_path / "store"))
    # routing persists only for pools that exist (the no-resurrect
    # rule shared with status): the controller always owns a spec
    store.apply(ScorerPoolSpec(name="p", artifact="a", version=1,
                               model_key="m"))
    t1 = {"keys": {"m": ("s0", "s1")}, "shards": {"s0": ["u0"]}}
    assert store.publish_routing("p", t1) == 1
    # content-identical republish (tuples vs lists, key order, an
    # embedded stale generation) does NOT bump: N routers comparing
    # generations must not see churn from idle reconcile passes
    t1b = {"shards": {"s0": ["u0"]}, "table_generation": 99,
           "keys": {"m": ["s0", "s1"]}}
    assert store.publish_routing("p", t1b) == 1
    doc = store.get_routing("p")
    assert doc["table_generation"] == 1
    assert doc["keys"]["m"] == ["s0", "s1"]
    # a real change bumps — and survives a fresh store instance
    t2 = {"keys": {"m": ["s1"]}, "shards": {"s0": ["u0"]}}
    assert store.publish_routing("p", t2) == 2
    assert DurablePoolStore(
        str(tmp_path / "store")).get_routing("p")["table_generation"] == 2
    # the split-brain fence: the epoch-1 holder is deposed by a
    # takeover to epoch 2 — its queued publish raises instead of
    # landing, even when the table content is unchanged
    assert store.acquire_lease("p", "op-a", ttl=0.05) == 1
    time.sleep(0.08)
    assert store.acquire_lease("p", "op-b", ttl=30.0) == 2
    with pytest.raises(StaleGenerationError):
        store.publish_routing("p", t2, epoch=1)
    assert store.get_routing("p")["table_generation"] == 2
    # the current holder's writes land normally
    assert store.publish_routing("p", {"keys": {}, "shards": {}},
                                 epoch=2) == 3


# ---------------------------------------------------------------------------
# Pod adoption on operator restart (fake replicas; real-subprocess leg
# in tools/chaos.py operator-restart)
# ---------------------------------------------------------------------------


class FakeAdopted(FakeReplica):
    """Already-running stand-in the adopted_factory hands back."""

    def __init__(self, manifest, version, spec):
        super().__init__(manifest["rid"], version, spec)
        self.port = manifest["port"]
        self._alive = True
        self._loaded = True

    def spawn(self):
        raise AssertionError("adopted replicas are never spawned")


def _manifest(dirpath, rid, pid=1000, port=7001, version=1):
    import os

    os.makedirs(dirpath, exist_ok=True)
    doc = {"rid": rid, "pool": "p", "pid": pid, "port": port,
           "version": version}
    with open(os.path.join(dirpath, f"{rid}.json"), "w") as f:
        json.dump(doc, f)
    return doc


def _ready_stats(rid, version, pid=1000, cordoned=None):
    return {"ready": True, "reasons": [], "cordoned": cordoned,
            "identity": {"pool": "p", "replica": rid, "pid": pid},
            "registry": {"m": {"version": version}}}


def _adoption_pool(tmp_path, replicas=2, version=1, probe=None,
                   pid_alive=None, **spec_kw):
    store = PoolStore()
    store.apply(ScorerPoolSpec(name="p", artifact="a", version=version,
                               model_key="m", replicas=replicas,
                               **spec_kw))
    rec = Reconciler(store, registry=None, pool="p",
                     replica_factory=FakeReplica,
                     workdir=str(tmp_path),
                     adopted_factory=FakeAdopted)
    if probe is not None:
        rec._probe_stats = probe
    rec._pid_alive = pid_alive or (lambda pid: True)
    return store, rec


def test_adopt_matching_never_duplicates(tmp_path, monkeypatch, mesh8):
    """A restarted operator ADOPTS its predecessor's live READY pods
    instead of spawning duplicates — zero replica_start events."""
    monkeypatch.setenv("H2O_TPU_POOL_DEREGISTER_GRACE", "0")
    mdir = str(tmp_path / "pods")
    _manifest(mdir, "p-1", port=7001)
    _manifest(mdir, "p-2", port=7002)
    # probe keyed off the port so each manifest matches its own rid
    store, rec = _adoption_pool(
        tmp_path, probe=lambda url: _ready_stats(
            "p-1" if url.endswith(":7001") else "p-2", 1))
    assert rec.adopt_existing() == 2
    assert _settle(rec)
    kinds = [e["kind"] for e in store.events("p")]
    assert kinds.count("replica_adopted") == 2
    assert "replica_start" not in kinds, \
        "adoption must not spawn duplicates"
    assert sorted(r.rid for r in rec.replicas) == ["p-1", "p-2"]
    assert all(r.state == READY for r in rec.replicas)
    # the rid sequence cleared the adopted ids: a later spawn cannot
    # collide with a live pod's identity
    assert rec._seq == 2


def test_adopt_stale_version_replaced_via_rollout(tmp_path,
                                                  monkeypatch, mesh8):
    """Adoptees on an old artifact version are adopted READY, then
    cordoned + replaced through the NORMAL surge-one convergence —
    an operator restart mid-rollout finishes the rollout."""
    monkeypatch.setenv("H2O_TPU_POOL_DEREGISTER_GRACE", "0")
    mdir = str(tmp_path / "pods")
    _manifest(mdir, "p-1", port=7001)
    _manifest(mdir, "p-2", port=7002)
    store, rec = _adoption_pool(tmp_path, version=2)   # spec wants v2
    rec._probe_stats = lambda url: _ready_stats(
        "p-1" if url.endswith(":7001") else "p-2", 1)  # pods run v1
    assert rec.adopt_existing() == 2
    assert not rec.converged()
    assert _settle(rec, passes=60)
    assert all(r.version == 2 and r.state == READY
               for r in rec.replicas)
    kinds = [e["kind"] for e in store.events("p")]
    # old replicas retired via cordon (never hard-killed) only after
    # a new-version READY existed
    assert kinds.index("replica_cordon") > kinds.index("replica_ready")


def test_adopt_stale_manifest_and_foreign_pod(tmp_path, mesh8):
    """Dead-pid manifests are cleaned up; a live port answering as
    someone else is left alone but its manifest is dropped. Both then
    converge through fresh spawns."""
    import os

    mdir = str(tmp_path / "pods")
    _manifest(mdir, "p-1", pid=111, port=7001)   # dead pid
    _manifest(mdir, "p-2", pid=222, port=7002)   # foreign identity
    store, rec = _adoption_pool(
        tmp_path,
        probe=lambda url: _ready_stats("OTHER", 1, pid=999),
        pid_alive=lambda pid: pid != 111)
    assert rec.adopt_existing() == 0
    kinds = [e["kind"] for e in store.events("p")]
    assert "adoption_stale" in kinds
    assert "adoption_foreign" in kinds
    assert os.listdir(mdir) == []        # both manifests dropped
    assert _settle(rec)
    assert sum(1 for e in store.events("p")
               if e["kind"] == "replica_start") == 2


def test_adopt_runs_before_reconcile_in_run(tmp_path, monkeypatch,
                                            mesh8):
    """run() adopts FIRST — a reconcile pass before adoption would
    spawn duplicates of every live pod."""
    monkeypatch.setenv("H2O_TPU_POOL_DEREGISTER_GRACE", "0")
    _manifest(str(tmp_path / "pods"), "p-1", port=7001)
    store, rec = _adoption_pool(tmp_path, replicas=1)
    rec._probe_stats = lambda url: _ready_stats("p-1", 1)
    stop = threading.Event()
    t = threading.Thread(target=rec.run, args=(stop,),
                         kwargs={"interval": 0.02}, daemon=True)
    t.start()
    assert rec.wait_converged(timeout=10)
    stop.set()
    t.join(timeout=5)
    kinds = [e["kind"] for e in store.events("p")]
    assert "replica_adopted" in kinds and "replica_start" not in kinds


# ---------------------------------------------------------------------------
# Crash-loop backoff + automatic rollout rollback
# ---------------------------------------------------------------------------


class CrashingReplica(FakeReplica):
    """Dies the instant it is observed (process exits right away)."""

    def spawn(self):
        super().spawn()
        self._alive = False


def test_crash_loop_backoff_spacing(tmp_path, monkeypatch, mesh8):
    """Respawns of a crash-looping replica are exponentially spaced:
    first replacement immediate, then >= base, >= 2*base... with the
    crash_loop_backoff event instead of a hot respawn loop."""
    import time

    monkeypatch.setenv("H2O_TPU_POOL_BACKOFF_BASE", "0.15")
    monkeypatch.setenv("H2O_TPU_POOL_BACKOFF_MAX", "5")
    store = PoolStore()
    store.apply(ScorerPoolSpec(name="p", artifact="a", version=1,
                               model_key="m", replicas=1))
    rec = Reconciler(store, registry=None, pool="p",
                     replica_factory=CrashingReplica)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 2.0:
        rec.reconcile_once()
        time.sleep(0.01)
    starts = [e["t"] for e in store.events("p")
              if e["kind"] == "replica_start"]
    kinds = [e["kind"] for e in store.events("p")]
    assert "crash_loop_backoff" in kinds
    assert len(starts) >= 4, f"crash loop never respawned: {kinds}"
    gaps = [b - a for a, b in zip(starts, starts[1:])]
    # gap 0 (first replacement) is free; then the exponential floor
    assert gaps[1] >= 0.15 - 0.02, gaps
    assert gaps[2] >= 0.30 - 0.02, gaps
    # a hot loop would fit dozens of spawns into 2s; backoff caps it
    assert len(starts) <= 8, f"{len(starts)} spawns in 2s: not spaced"
    # status surfaces the wait
    st = store.get_status("p")
    assert "crash_loop" in st and st["crash_loop"]["version"] == 1


class V2FailsReplica(FakeReplica):
    """v2 fails its push (the poison artifact shape); other versions
    behave."""

    def start_load(self, registry):
        if self.version == 2:
            self.state = LOADING
            self._load_done = True        # finished, with an error
        else:
            super().start_load(registry)

    def load_error(self):
        return "boom: poison artifact" if self.version == 2 else None


def test_rollout_rollback_pins_last_good(tmp_path, monkeypatch, mesh8):
    """A rollout whose new version fails readiness ROLLOUT_RETRIES
    times auto-rolls-back: rollout_rolled_back fires, status pins the
    last-good version, old replicas are never disturbed, and the pool
    re-converges on last-good."""
    monkeypatch.setenv("H2O_TPU_POOL_DEREGISTER_GRACE", "0")
    monkeypatch.setenv("H2O_TPU_POOL_BACKOFF_BASE", "0")
    monkeypatch.setenv("H2O_TPU_POOL_ROLLOUT_RETRIES", "3")
    store = PoolStore()
    store.apply(ScorerPoolSpec(name="p", artifact="a", version=1,
                               model_key="m", replicas=2))
    rec = Reconciler(store, registry=None, pool="p",
                     replica_factory=V2FailsReplica)
    assert _settle(rec)
    old_rids = sorted(r.rid for r in rec.replicas)
    store.apply_update("p", version=2)
    assert _settle(rec, passes=80), store.get_status("p")
    kinds = [e["kind"] for e in store.events("p")]
    assert "rollout_rolled_back" in kinds
    assert kinds.count("replica_load_failed") == 3
    st = store.get_status("p")
    assert st["rollout"] == {"failed_version": 2, "pinned_version": 1,
                             "state": "rolled_back"}
    assert st["effective_version"] == 1 and st["desired_version"] == 2
    # the old replicas were NEVER disturbed: same rids, still READY v1
    assert sorted(r.rid for r in rec.replicas) == old_rids
    assert all(r.state == READY and r.version == 1
               for r in rec.replicas)
    assert "replica_cordon" not in kinds
    # a NEW version supersedes the pin and rolls normally
    store.apply_update("p", version=3)
    assert _settle(rec, passes=80)
    assert all(r.version == 3 for r in rec.replicas)


def test_rollback_state_survives_restart(tmp_path, mesh8):
    """A restarted operator resumes the rollback pin from the durable
    store's status instead of re-trying the failed version."""
    store = DurablePoolStore(str(tmp_path / "store"))
    store.apply(ScorerPoolSpec(name="p", artifact="a", version=2,
                               model_key="m", replicas=1))
    store.set_status("p", {"last_good_version": 1,
                           "rollout": {"failed_version": 2,
                                       "pinned_version": 1,
                                       "state": "rolled_back"}})
    rec = Reconciler(store, registry=None, pool="p",
                     replica_factory=FakeReplica)
    spec, _ = store.get("p")
    assert rec._want_version(spec) == 1      # pinned, not re-tried
    assert rec._last_good == 1
    # a fresh version bump clears the pin
    store.apply_update("p", version=3)
    spec, _ = store.get("p")
    assert rec._want_version(spec) == 3


def test_probe_timeout_knob(monkeypatch, mesh8):
    from h2o_kubernetes_tpu.operator.reconcile import _probe_timeout

    assert _probe_timeout() == 2.0
    monkeypatch.setenv("H2O_TPU_POOL_PROBE_TIMEOUT", "0.7")
    assert _probe_timeout() == 0.7
    monkeypatch.setenv("H2O_TPU_POOL_PROBE_TIMEOUT", "0")
    assert _probe_timeout() == 0.1           # floored, never hangs
