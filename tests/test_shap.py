"""TreeSHAP (predict_contributions) tests.

Two independent checks, mirroring how the reference validates its
h2o-genmodel TreeSHAP: (1) the additivity invariant — contributions sum
to the raw margin for every row; (2) exact agreement with brute-force
Shapley values computed by subset enumeration over the tree's
cover-weighted conditional expectations."""

import itertools

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import DRF, GBM

# long-running tier: deselect locally with -m 'not slow'
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def frame():
    rng = np.random.default_rng(13)
    n = 300
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)   # noise feature
    g = np.array(["p", "q", "r"])[rng.integers(0, 3, n)]
    logit = 1.5 * x0 - x1 + (g == "p") * 0.8
    return h2o.Frame.from_arrays({
        "x0": x0, "x1": x1, "x2": x2, "g": g,
        "y": np.where(logit + rng.normal(scale=0.3, size=n) > 0,
                      "yes", "no")})


def _margin(model, fr):
    import jax.numpy as jnp

    X = model._design_matrix(fr)
    return np.asarray(model._margins(X))[: fr.nrows]


def test_additivity_binomial(frame):
    m = GBM(ntrees=8, max_depth=4, seed=3).train(
        y="y", training_frame=frame)
    contrib = m.predict_contributions(frame)
    total = sum(contrib.vec(n).to_numpy()
                for n in contrib.names)
    np.testing.assert_allclose(total, _margin(m, frame),
                               rtol=1e-4, atol=1e-4)


def test_additivity_regression_with_nas():
    rng = np.random.default_rng(7)
    n = 200
    x0 = rng.normal(size=n).astype(np.float32)
    x0[::11] = np.nan
    x1 = rng.normal(size=n).astype(np.float32)
    y = (2 * np.nan_to_num(x0) - x1
         + rng.normal(scale=0.2, size=n)).astype(np.float32)
    fr = h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": y})
    m = GBM(ntrees=5, max_depth=3, seed=1).train(
        y="y", training_frame=fr)
    contrib = m.predict_contributions(fr)
    total = sum(contrib.vec(c).to_numpy() for c in contrib.names)
    np.testing.assert_allclose(total, _margin(m, fr),
                               rtol=1e-4, atol=1e-4)


def test_additivity_drf(frame):
    m = DRF(ntrees=6, max_depth=3, seed=5).train(
        y="y", training_frame=frame)
    contrib = m.predict_contributions(frame)
    total = sum(contrib.vec(c).to_numpy() for c in contrib.names)
    np.testing.assert_allclose(total, _margin(m, frame),
                               rtol=1e-4, atol=1e-4)


def test_noise_feature_gets_small_contributions(frame):
    m = GBM(ntrees=10, max_depth=4, seed=3).train(
        y="y", training_frame=frame)
    contrib = m.predict_contributions(frame)
    mean_abs = {n: float(np.abs(contrib.vec(n).to_numpy()).mean())
                for n in ("x0", "x1", "x2")}
    assert mean_abs["x2"] < 0.3 * mean_abs["x0"]


def test_multinomial_rejected():
    rng = np.random.default_rng(2)
    n = 120
    x = rng.normal(size=n).astype(np.float32)
    y = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    m = GBM(ntrees=2, max_depth=2, seed=0).train(
        y="y", training_frame=fr)
    with pytest.raises(ValueError, match="binomial and regression"):
        m.predict_contributions(fr)


# -- brute-force Shapley cross-check ----------------------------------------

def _expvalue(sp, sf, sb, nl, val, cov, binned_row, na_bin, j, S):
    """Cover-weighted conditional expectation E[f(x) | x_S] of the
    path-dependent perturbation — the quantity TreeSHAP is exact for."""
    if not sp[j]:
        return float(val[j])
    d = int(sf[j])
    lc, rc = 2 * j + 1, 2 * j + 2
    if d in S:
        b = binned_row[d]
        go_right = (~nl[j]) if b == na_bin else (b > sb[j])
        return _expvalue(sp, sf, sb, nl, val, cov, binned_row, na_bin,
                         rc if go_right else lc, S)
    cj = max(float(cov[j]), 1e-12)
    return (float(cov[lc]) / cj * _expvalue(
        sp, sf, sb, nl, val, cov, binned_row, na_bin, lc, S)
        + float(cov[rc]) / cj * _expvalue(
        sp, sf, sb, nl, val, cov, binned_row, na_bin, rc, S))


def test_matches_bruteforce_shapley():
    rng = np.random.default_rng(17)
    n = 150
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)
    y = (x0 + 0.5 * x1 * x2
         + rng.normal(scale=0.2, size=n)).astype(np.float32)
    fr = h2o.Frame.from_arrays({"x0": x0, "x1": x1, "x2": x2, "y": y})
    m = GBM(ntrees=2, max_depth=3, seed=9).train(
        y="y", training_frame=fr)
    contrib = m.predict_contributions(fr)

    from h2o_kubernetes_tpu.models.tree.binning import apply_bins
    import jax.numpy as jnp

    X = m._design_matrix(fr)
    binned = np.asarray(apply_bins(X, m._edges, m._enum_mask,
                                   m.bin_spec.na_bin))[: fr.nrows]
    F = 3
    import math

    fact = [math.factorial(k) for k in range(F + 1)]
    trees = {f: np.asarray(getattr(m.trees, f))
             for f in ("split_feat", "split_bin", "na_left", "is_split",
                       "value", "cover")}
    rows = [0, 3, 17, 42]
    for r in rows:
        phi = np.zeros(F)
        for t in range(trees["split_feat"].shape[0]):
            a = (trees["is_split"][t], trees["split_feat"][t],
                 trees["split_bin"][t], trees["na_left"][t],
                 trees["value"][t], trees["cover"][t])
            for d in range(F):
                others = [f for f in range(F) if f != d]
                for k in range(F):
                    for S in itertools.combinations(others, k):
                        wgt = fact[k] * fact[F - k - 1] / fact[F]
                        with_d = _expvalue(*a, binned[r],
                                           m.bin_spec.na_bin, 0,
                                           set(S) | {d})
                        without = _expvalue(*a, binned[r],
                                            m.bin_spec.na_bin, 0,
                                            set(S))
                        phi[d] += wgt * (with_d - without)
        got = np.array([contrib.vec(f"x{i}").to_numpy()[r]
                        for i in range(F)])
        np.testing.assert_allclose(got, phi, rtol=1e-4, atol=1e-4)


# -- partial dependence ------------------------------------------------------

def test_partial_plot_monotone_feature(frame):
    m = GBM(ntrees=8, max_depth=3, seed=3).train(
        y="y", training_frame=frame)
    (pd_x0,) = m.partial_plot(frame, ["x0"], nbins=8)
    assert pd_x0.names == ["x0", "mean_response", "stddev_response",
                           "std_error_mean_response"]
    mr = pd_x0.vec("mean_response").to_numpy()
    # y ~ 1.5*x0 ... : mean response must rise with x0
    assert mr[-1] > mr[0] + 0.2


def test_partial_plot_enum_column():
    rng = np.random.default_rng(23)
    n = 300
    g = np.array(["p", "q", "r"])[rng.integers(0, 3, n)]
    x = rng.normal(size=n).astype(np.float32)
    logit = (g == "p") * 2.0 - 1.0 + 0.2 * x
    fr = h2o.Frame.from_arrays({
        "g": g, "x": x,
        "y": np.where(logit + rng.normal(scale=0.3, size=n) > 0,
                      "yes", "no")})
    m = GBM(ntrees=8, max_depth=3, seed=3).train(
        y="y", training_frame=fr)
    (pd_g,) = m.partial_plot(fr, ["g"])
    assert pd_g.nrows == 3                 # one row per level
    assert pd_g.vec("g").domain == ["p", "q", "r"]
    mr = dict(zip(["p", "q", "r"], pd_g.vec("mean_response").to_numpy()))
    assert mr["p"] > mr["q"] + 0.2         # level p dominates the logit


def test_partial_plot_enum_uses_training_domain():
    """Scoring frame missing a training level must still sweep (and
    label) the TRAINING domain, not the scoring frame's code space."""
    rng = np.random.default_rng(29)
    n = 300
    g = np.array(["p", "q", "r"])[rng.integers(0, 3, n)]
    x = rng.normal(size=n).astype(np.float32)
    logit = (g == "p") * 2.0 - 1.0 + 0.2 * x
    fr = h2o.Frame.from_arrays({
        "g": g, "x": x,
        "y": np.where(logit + rng.normal(scale=0.3, size=n) > 0,
                      "yes", "no")})
    m = GBM(ntrees=8, max_depth=3, seed=3).train(
        y="y", training_frame=fr)
    sub = np.flatnonzero(g != "p")           # no 'p' rows at all
    score_fr = fr.select_rows(sub)
    # select_rows keeps the domain; rebuild with a narrowed one
    score_fr = h2o.Frame.from_arrays({
        "g": np.asarray(g[sub]), "x": x[sub],
        "y": np.asarray(["yes"] * len(sub))})
    assert score_fr.vec("g").domain == ["q", "r"]
    (pd_g,) = m.partial_plot(score_fr, ["g"])
    assert pd_g.vec("g").domain == ["p", "q", "r"]
    assert pd_g.nrows == 3
    mr = dict(zip(["p", "q", "r"], pd_g.vec("mean_response").to_numpy()))
    assert mr["p"] > mr["q"] + 0.2           # 'p' still dominates


# -- leaf node assignment ----------------------------------------------------

def test_leaf_node_assignment_consistent_with_predictions(frame):
    import jax.numpy as jnp
    from h2o_kubernetes_tpu.models.gbm import _heap_path

    m = GBM(ntrees=4, max_depth=3, seed=3).train(
        y="y", training_frame=frame)
    la = m.predict_leaf_node_assignment(frame, type="Node_ID")
    assert la.names == ["T1", "T2", "T3", "T4"]
    # rebuilding the margin from assigned leaves reproduces _margins
    vals = np.asarray(m.trees.value)                      # [T, N]
    total = sum(vals[t][la.vec(f"T{t+1}").to_numpy().astype(int)]
                for t in range(4))
    want = _margin(m, frame) - float(m.init_score)
    np.testing.assert_allclose(total, want, rtol=1e-4, atol=1e-4)
    # Path form round-trips the heap index
    lp = m.predict_leaf_node_assignment(frame, type="Path")
    v = lp.vec("T1")
    node0 = int(la.vec("T1").to_numpy()[0])
    assert v.domain[int(v.to_numpy()[0])] == _heap_path(node0)


def test_heap_path_encoding():
    from h2o_kubernetes_tpu.models.gbm import _heap_path

    assert _heap_path(0) == ""
    assert _heap_path(1) == "L"
    assert _heap_path(2) == "R"
    assert _heap_path(3) == "LL"
    assert _heap_path(6) == "RR"
    assert _heap_path(9) == "LRL"
