"""Failure detection (SURVEY.md §5.3) + AutoML checkpoint-resume (§5.4).

The reference detects node loss via heartbeats and fails fast (locked
cloud, jobs fail cleanly, no elasticity); recovery is out-of-band. The
TPU build mirrors that: a collective liveness probe, `doall` raising on
an unhealthy cluster, and resume via the AutoML manifest.
"""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.runtime import health
from h2o_kubernetes_tpu.runtime.mrtask import doall


@pytest.fixture(autouse=True)
def _fresh_health():
    health.reset()
    yield
    health.reset()


def test_heartbeat_probe_succeeds(mesh8):
    assert health.heartbeat(timeout=120.0)
    st = health.health_status()
    assert st["healthy"] and st["beats"] == 1 and st["last_beat"]
    assert h2o.cluster_status()["cloud_healthy"]


def test_unhealthy_cluster_fails_fast(mesh8):
    import jax.numpy as jnp

    health.mark_unhealthy("simulated chip loss")
    with pytest.raises(health.ClusterHealthError, match="simulated"):
        doall(lambda x: {"s": jnp.sum(x)},
              jnp.ones(16), reduce="sum")
    assert not h2o.cluster_status()["cloud_healthy"]
    health.reset()                      # restart semantics
    out = doall(lambda x: {"s": jnp.sum(x)}, jnp.ones(16), reduce="sum")
    assert float(out["s"]) == 16.0


def _toy_frame(n=300, seed=5):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    y = np.where(x0 + 0.5 * x1 + rng.normal(scale=0.4, size=n) > 0,
                 "y", "n")
    return h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": y})


@pytest.mark.slow
def test_automl_resume_from_manifest(tmp_path, mesh8):
    fr = _toy_frame()
    kw = dict(nfolds=2, seed=3, project_name="resume_t",
              include_algos=["gbm", "glm"], verbosity=None,
              checkpoint_dir=str(tmp_path))
    a1 = h2o.AutoML(max_models=2, **kw)
    a1.train(y="y", training_frame=fr)
    ids1 = [r["model_id"] for r in a1.leaderboard.rows]
    assert len(ids1) == 2
    assert (tmp_path / "automl_manifest.json").exists()

    # a rerun with a larger budget resumes the finished steps (no
    # retraining) and continues with new ones
    a2 = h2o.AutoML(max_models=4, **kw)
    a2.train(y="y", training_frame=fr)
    ids2 = [r["model_id"] for r in a2.leaderboard.rows]
    assert set(ids1) <= set(ids2)
    assert len([i for i in ids2 if "Ensemble" not in i]) == 4
    # resumed models predict
    m = a2.leaderboard.models[ids1[0]]
    assert m.predict(fr).nrows == fr.nrows


def test_automl_job_fails_cleanly_on_dead_cluster(mesh8):
    fr = _toy_frame()
    health.mark_unhealthy("simulated failure")
    a = h2o.AutoML(max_models=1, nfolds=2, include_algos=["gbm"],
                   project_name="failfast_t", verbosity=None)
    with pytest.raises(health.ClusterHealthError):
        a.train(y="y", training_frame=fr)
    assert a.job.status == "FAILED"


def test_gbm_fails_fast_mid_train(mesh8, monkeypatch):
    """VERDICT r2 item 6: a mesh that dies MID-train must surface as
    ClusterHealthError at the next chunk boundary, not a hang/crash —
    the tree core dispatches shard_map directly, bypassing doall."""
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.models import gbm as gbm_mod

    rng = np.random.default_rng(5)
    n = 500
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x > 0, "p", "n")
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    # force one tree per dispatch so the loop has chunk boundaries
    monkeypatch.setattr(gbm_mod, "_DISPATCH_BUDGET", 1)
    orig = gbm_mod.boost_trees
    calls = {"n": 0}

    def dying_boost(*a, **kw):
        out = orig(*a, **kw)
        calls["n"] += 1
        if calls["n"] == 2:         # mesh dies after the second chunk
            health.mark_unhealthy("ICI link down (test)")
        return out

    monkeypatch.setattr(gbm_mod, "boost_trees", dying_boost)
    try:
        with pytest.raises(health.ClusterHealthError):
            GBM(ntrees=6, max_depth=3, seed=0).train(
                y="y", training_frame=fr)
    finally:
        health.reset()
    assert calls["n"] == 2          # no further dispatch after death


def test_glm_fails_fast_mid_train(mesh8, monkeypatch):
    from h2o_kubernetes_tpu.models import GLM
    from h2o_kubernetes_tpu.models import glm as glm_mod

    rng = np.random.default_rng(6)
    n = 400
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    orig = glm_mod._gram_task
    calls = {"n": 0}

    def dying_gram(*a, **kw):
        out = orig(*a, **kw)
        calls["n"] += 1
        if calls["n"] == 2:
            health.mark_unhealthy("chip hang (test)")
        return out

    monkeypatch.setattr(glm_mod, "_gram_task", dying_gram)
    try:
        with pytest.raises(health.ClusterHealthError):
            # binomial iterates (gaussian-identity solves in one shot);
            # zero tolerances keep it iterating past the failure point
            GLM(family="binomial", max_iterations=20,
                objective_epsilon=0.0, beta_epsilon=0.0).train(
                    y="y", training_frame=fr)
    finally:
        health.reset()
