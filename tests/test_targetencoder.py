"""TargetEncoder tests (H2OTargetEncoderEstimator analog) plus the
impute/table/quantile/unique munging surface."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import TargetEncoder

# long-running tier: deselect locally with -m 'not slow'
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def te_frame():
    rng = np.random.default_rng(5)
    n = 400
    cat = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    base = {"a": 0.8, "b": 0.6, "c": 0.3, "d": 0.1}
    y = (rng.random(n) < np.vectorize(base.get)(cat)).astype(np.float32)
    fold = (np.arange(n) % 3).astype(np.float32)
    return h2o.Frame.from_arrays({
        "cat": cat, "fold": fold,
        "x": rng.normal(size=n).astype(np.float32), "y": y}), cat, y


def test_none_mode_encodes_level_means(te_frame):
    fr, cat, y = te_frame
    model = TargetEncoder(noise=0.0).train(
        y="y", training_frame=fr, x=["cat"])
    out = model.transform(fr)
    enc = out.vec("cat_te").to_numpy()
    for lvl in "abcd":
        want = y[cat == lvl].mean()
        got = enc[cat == lvl]
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_loo_excludes_own_row(te_frame):
    fr, cat, y = te_frame
    model = TargetEncoder(data_leakage_handling="leave_one_out",
                          noise=0.0).train(
        y="y", training_frame=fr, x=["cat"])
    out = model.transform(fr, as_training=True, noise=0.0)
    enc = out.vec("cat_te").to_numpy()
    i = 0
    lvl = cat[i]
    sel = cat == lvl
    want = (y[sel].sum() - y[i]) / (sel.sum() - 1)
    assert abs(enc[i] - want) < 1e-5


def test_kfold_uses_out_of_fold_stats(te_frame):
    fr, cat, y = te_frame
    fold = np.asarray(fr.vec("fold").to_numpy()).astype(int)
    model = TargetEncoder(data_leakage_handling="k_fold",
                          fold_column="fold", noise=0.0).train(
        y="y", training_frame=fr, x=["cat"])
    out = model.transform(fr, as_training=True, noise=0.0)
    enc = out.vec("cat_te").to_numpy()
    i = 7
    sel = (cat == cat[i]) & (fold != fold[i])
    want = y[sel].mean()
    assert abs(enc[i] - want) < 1e-5
    # scoring transform ignores folds
    out2 = model.transform(fr)
    enc2 = out2.vec("cat_te").to_numpy()
    sel_all = cat == cat[i]
    assert abs(enc2[i] - y[sel_all].mean()) < 1e-5


def test_blending_shrinks_rare_levels():
    rng = np.random.default_rng(1)
    n = 200
    cat = np.array(["common"] * (n - 2) + ["rare"] * 2)
    y = np.concatenate([
        (rng.random(n - 2) < 0.3).astype(np.float32),
        np.ones(2, dtype=np.float32)])
    fr = h2o.Frame.from_arrays({"cat": cat, "y": y})
    m = TargetEncoder(blending=True, inflection_point=10, smoothing=5,
                      noise=0.0).train(y="y", training_frame=fr,
                                       x=["cat"])
    enc = m.transform(fr).vec("cat_te").to_numpy()
    rare_enc = enc[cat == "rare"][0]
    prior = y.mean()
    # rare level (n=2, raw mean 1.0): lambda = sigma((2-10)/5) ~ 0.17,
    # so the encoding shrinks most of the way back toward the prior
    lam = 1.0 / (1.0 + np.exp((10 - 2) / 5))
    want = lam * 1.0 + (1 - lam) * prior
    assert abs(rare_enc - want) < 1e-5, (rare_enc, want)
    assert prior < rare_enc < 0.5


def test_unseen_level_and_na_get_prior(te_frame):
    fr, cat, y = te_frame
    model = TargetEncoder(noise=0.0).train(
        y="y", training_frame=fr, x=["cat"])
    new = h2o.Frame.from_arrays({
        "cat": np.array(["a", "zzz", "b"]),
        "y": np.zeros(3, dtype=np.float32)})
    enc = model.transform(new).vec("cat_te").to_numpy()
    assert abs(enc[1] - model.prior) < 1e-6
    assert abs(enc[0] - y[cat == "a"].mean()) < 1e-5


def test_training_noise_applied(te_frame):
    fr, cat, y = te_frame
    model = TargetEncoder(noise=0.05).train(
        y="y", training_frame=fr, x=["cat"])
    a = model.transform(fr, as_training=True).vec("cat_te").to_numpy()
    b = model.transform(fr).vec("cat_te").to_numpy()
    d = np.abs(a - b)
    assert d.max() <= 0.05 + 1e-6
    assert d.mean() > 0.005       # noise actually applied


def test_te_feeds_gbm(te_frame):
    """End-to-end: encode then train — the high-cardinality recipe."""
    fr, cat, y = te_frame
    model = TargetEncoder(noise=0.0).train(
        y="y", training_frame=fr, x=["cat"])
    enc = model.transform(fr)
    from h2o_kubernetes_tpu.models import GBM

    fr2 = h2o.Frame.from_arrays({
        "cat_te": enc.vec("cat_te").to_numpy(),
        "x": fr.vec("x").to_numpy(),
        "y": np.where(y > 0, "yes", "no")})
    m = GBM(ntrees=5, max_depth=3, seed=1).train(
        y="y", training_frame=fr2)
    assert m.model_performance(fr2, "y")["auc"] > 0.6


def test_estimator_alias():
    from h2o_kubernetes_tpu.estimators import H2OTargetEncoderEstimator

    assert H2OTargetEncoderEstimator is TargetEncoder


# -- munge surface -----------------------------------------------------------

def test_impute_mean_and_mode():
    x = np.array([1.0, 2.0, np.nan, 3.0], dtype=np.float32)
    g = np.array(["u", "v", "u", None])
    fr = h2o.Frame.from_arrays({"x": x, "g": g})
    fill = fr.impute("x", method="mean")
    assert abs(fill - 2.0) < 1e-6
    assert not np.isnan(fr.vec("x").to_numpy()).any()
    lvl = fr.impute("g", method="mode")
    assert lvl == "u"
    assert (fr.vec("g").to_numpy() >= 0).all()


def test_impute_grouped_mean():
    x = np.array([1.0, 3.0, np.nan, 10.0, np.nan], dtype=np.float32)
    g = np.array(["a", "a", "a", "b", "b"])
    fr = h2o.Frame.from_arrays({"x": x, "g": g})
    fr.impute("x", method="mean", by="g")
    got = fr.vec("x").to_numpy()
    assert abs(got[2] - 2.0) < 1e-5       # mean of group a
    assert abs(got[4] - 10.0) < 1e-5      # mean of group b


def test_table_counts():
    g = np.array(["a", "b", "a", "a", None])
    h_ = np.array(["x", "x", "y", "x", "y"])
    fr = h2o.Frame.from_arrays({"g": g, "h": h_})
    t = fr.table("g")
    d = dict(zip([t.vec("g").domain[int(c)] for c in
                  t.vec("g").to_numpy()],
                 t.vec("Count").to_numpy()))
    assert d == {"a": 3.0, "b": 1.0}
    t2 = fr.table("g", "h")
    assert float(t2.vec("Count").to_numpy().sum()) == 4.0


def test_quantile_and_unique():
    x = np.arange(101, dtype=np.float32)
    fr = h2o.Frame.from_arrays({"x": x})
    q = fr.quantile(prob=[0.5, 0.9])
    got = q.vec("x").to_numpy()
    np.testing.assert_allclose(got, [50.0, 90.0], atol=0.5)
    u = h2o.Frame.from_arrays(
        {"v": np.array([3.0, 1.0, 3.0, np.nan], dtype=np.float32)})
    vals = u.vec("v").unique().vec("v").to_numpy()
    np.testing.assert_allclose(vals, [1.0, 3.0])


def test_loo_training_transform_requires_y(te_frame):
    fr, cat, y = te_frame
    model = TargetEncoder(data_leakage_handling="leave_one_out",
                          noise=0.0).train(
        y="y", training_frame=fr, x=["cat"])
    no_y = fr.drop("y")
    with pytest.raises(ValueError, match="response column"):
        model.transform(no_y, as_training=True)
    # scoring transform (no leakage handling) works without y
    out = model.transform(no_y)
    assert "cat_te" in out.names


def test_impute_preserves_time_kind():
    t = np.array(["2024-01-01", "NaT", "2024-01-03"],
                 dtype="datetime64[ms]")
    fr = h2o.Frame.from_arrays({"ts": t})
    assert fr.vec("ts").kind == "time"
    fr.impute("ts", method="mean")
    v = fr.vec("ts")
    assert v.kind == "time"
    got = v.to_numpy()
    want_mid = (t[0].astype("datetime64[ms]").astype(np.float64)
                + t[2].astype("datetime64[ms]").astype(np.float64)) / 2
    assert abs(got[1] - want_mid) < 1000        # within a second
