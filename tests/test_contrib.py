"""Compiled TreeSHAP serving (ISSUE 10 tentpole): the device
path-enumeration kernel must match the f64 host recursion on the rich
fixtures (NAs, grouped high-card enums, weights, DRF scaling, laplace
margin_scale), hold the on-device additivity invariant, survive
evict→promote bitwise, serve from registry artifacts bitwise vs the
training-side model, expose itself on the XGBoost estimator surface,
and turn every precondition failure into a clean 400 on the REST
contributions route."""

import io
import json
import socket
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest
from h2o_kubernetes_tpu.models import DRF, GBM, XGBoost
from h2o_kubernetes_tpu.models.base import (evict_scorer_cache,
                                            model_scorer_counters)


def _rich_frame(n=500, seed=7, nlevels=60):
    """Numeric-with-NA + low-card enum + HIGH-card enum (grouped code
    ranges at nbins=64) + weights + binary response — the flat-scorer
    parity matrix, minus offset (contributions reject it)."""
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x0[::17] = np.nan
    x1 = rng.exponential(2.0, size=n).astype(np.float32)
    g = np.array([f"L{i}" for i in range(nlevels)])[
        rng.integers(0, nlevels, n)]
    c = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    y = np.where(np.nan_to_num(x0) + (c == "a")
                 + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays(
        {"x0": x0, "x1": x1, "g": g, "c": c, "w": w, "y": y})


def _host_phi(m, fr) -> np.ndarray:
    contrib = m.predict_contributions(fr)
    return np.stack([contrib.vec(c).to_numpy() for c in contrib.names],
                    axis=1)


def _X(m, fr) -> np.ndarray:
    return np.asarray(m._design_matrix(fr))[: fr.nrows]


def _assert_device_contract(m, fr, tol=1e-4):
    """Device-vs-host parity + on-device additivity, the tentpole's
    two numerical assertions."""
    import jax.numpy as jnp

    X = _X(m, fr)
    dev = m.contrib_numpy(X)
    host = _host_phi(m, fr)
    assert dev.shape == host.shape
    np.testing.assert_allclose(dev, host, rtol=tol, atol=tol)
    margins = np.asarray(m._margins(jnp.asarray(X)))[: fr.nrows]
    np.testing.assert_allclose(dev.sum(axis=1), margins,
                               rtol=tol, atol=tol)
    return dev


def test_device_matches_host_rich_binomial(mesh8):
    fr = _rich_frame()
    m = GBM(ntrees=8, max_depth=4, nbins=64, seed=1).train(
        y="y", training_frame=fr, weights_column="w")
    _assert_device_contract(m, fr)


def test_device_matches_host_drf_scale(mesh8):
    fr = _rich_frame(n=400, seed=11)
    m = DRF(ntrees=5, max_depth=3, seed=5).train(
        y="y", training_frame=fr)
    _assert_device_contract(m, fr)


def test_device_matches_host_laplace_margin_scale(mesh8):
    rng = np.random.default_rng(3)
    n = 400
    x = rng.normal(size=n).astype(np.float32)
    x[::11] = np.nan
    y = (2.0 * np.nan_to_num(x)
         + rng.normal(scale=0.3, size=n)).astype(np.float32)
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    m = GBM(ntrees=5, max_depth=3, distribution="laplace",
            seed=2).train(y="y", training_frame=fr)
    assert m.margin_scale != 1.0       # the scaled path, not a no-op
    _assert_device_contract(m, fr)


def test_dp_fallback_kernel_matches_host(mesh8, monkeypatch):
    """The direct extend/unwind DP kernel (flat_shap) serves ensembles
    too deep for a pattern table — every other test/gate/bench model
    is shallow enough to take flat_shap_tab, so pin the fallback
    explicitly by forcing the pattern-table gate shut."""
    from h2o_kubernetes_tpu.models.tree import shap as S

    monkeypatch.setattr(S, "_PATTERN_TABLE_MAX_BYTES", 0)
    fr = _rich_frame(n=400, seed=41)
    m = GBM(ntrees=5, max_depth=4, nbins=64, seed=2).train(
        y="y", training_frame=fr, weights_column="w")
    dev = _assert_device_contract(m, fr)
    assert all(c is None for c in m._shap_ctab_np)   # DP path ran
    # and the two kernels agree with each other: rebuild with the
    # pattern tables enabled on a fresh prepare
    monkeypatch.setattr(S, "_PATTERN_TABLE_MAX_BYTES", 64 << 20)
    evict_scorer_cache(m)
    for k in ("_shap_tables_np", "_shap_ctab_np"):
        m.__dict__.pop(k, None)
    dev_tab = m.contrib_numpy(_X(m, fr))
    assert any(c is not None for c in m._shap_ctab_np)
    np.testing.assert_allclose(dev_tab, dev, rtol=1e-5, atol=1e-6)


def test_contrib_evict_promote_bitwise(mesh8):
    fr = _rich_frame(n=300, seed=19)
    m = GBM(ntrees=4, max_depth=3, nbins=64, seed=3).train(
        y="y", training_frame=fr)
    X = _X(m, fr)
    phi1 = m.contrib_numpy(X)
    ctr0 = model_scorer_counters(m)
    evict_scorer_cache(m)
    # eviction drops the DEVICE tables + executables; host tables stay
    assert "_shap_tables" not in m.__dict__
    assert "_shap_tables_np" in m.__dict__
    phi2 = m.contrib_numpy(X)
    assert np.array_equal(phi1, phi2)
    ctr1 = model_scorer_counters(m)
    assert ctr1["promotions"] > ctr0["promotions"]


def test_contrib_warm_up_then_zero_misses(mesh8):
    fr = _rich_frame(n=300, seed=23)
    m = GBM(ntrees=3, max_depth=3, nbins=64, seed=3).train(
        y="y", training_frame=fr)
    X = _X(m, fr)
    m.warm_up([256], contributions=True)
    c0 = model_scorer_counters(m)
    m.contrib_numpy(X[:50])
    m.contrib_numpy(X[:200])
    m.score_numpy(X[:200])
    c1 = model_scorer_counters(m)
    assert c1["misses"] == c0["misses"]      # both programs warm


def test_xgboost_contrib_parity_on_identical_trees(mesh8):
    """XGBoost shares the GBM tree stack: with the regularization
    knobs aligned the two estimators grow IDENTICAL trees, and their
    contributions must agree exactly."""
    rng = np.random.default_rng(9)
    n = 400
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    y = (x0 - 0.5 * x1 + rng.normal(scale=0.3, size=n)).astype(
        np.float32)
    fr = h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": y})
    mg = GBM(ntrees=4, max_depth=3, learn_rate=0.3, min_rows=1.0,
             min_split_improvement=0.0, seed=2).train(
        y="y", training_frame=fr)
    mx = XGBoost(ntrees=4, max_depth=3, eta=0.3, reg_lambda=0.0,
                 gamma=0.0, min_child_weight=0.0, seed=2).train(
        y="y", training_frame=fr)
    for f in ("split_feat", "split_bin", "na_left", "is_split",
              "value", "cover"):
        assert np.array_equal(np.asarray(getattr(mg.trees, f)),
                              np.asarray(getattr(mx.trees, f))), f
    hg, hx = _host_phi(mg, fr), _host_phi(mx, fr)
    assert np.array_equal(hg, hx)
    # the serving kernel agrees on the xgboost surface too
    _assert_device_contract(mx, fr)


def test_registry_scorer_contrib_bitwise_and_coverless_reject(mesh8):
    from h2o_kubernetes_tpu.mojo import export_mojo
    from h2o_kubernetes_tpu.operator.registry import (FlatTreeScorer,
                                                      load_artifact)

    fr = _rich_frame(n=300, seed=29)
    m = GBM(ntrees=4, max_depth=3, nbins=64, seed=5).train(
        y="y", training_frame=fr)
    X = _X(m, fr)
    want = m.contrib_numpy(X)
    buf = io.BytesIO()
    export_mojo(m, buf)
    fts = load_artifact(buf.getvalue())
    assert fts.contrib_support() is None
    got = fts.contrib_numpy(X)
    # registry-pushed artifact serves contributions BITWISE-identical
    # to the training-side model (same tables -> same HLO)
    assert np.array_equal(got, want)
    # an artifact without the cover part keeps serving margins but
    # rejects contributions with the re-export message
    arrays = {k: v for k, v in fts._artifact_arrays.items()
              if k != "flat_cover"}
    bare = FlatTreeScorer(fts._artifact_meta, arrays)
    reason = bare.contrib_support()
    assert reason is not None and "re-export" in reason
    with pytest.raises(ValueError, match="re-export"):
        bare.contrib_numpy(X)
    assert bare.score_numpy(X).shape[0] == X.shape[0]


def test_pre_cover_model_rejected_everywhere(mesh8):
    """The persist.py NaN-cover sentinel (pre-cover pickles) must
    reject through BOTH the host accessor and the serving entry with
    the retrain message — and never through a traceback."""
    fr = _rich_frame(n=300, seed=31)
    m = GBM(ntrees=3, max_depth=2, nbins=64, seed=1).train(
        y="y", training_frame=fr)
    m.trees = m.trees._replace(cover=np.full(
        np.asarray(m.trees.cover).shape, np.nan, np.float32))
    with pytest.raises(ValueError, match="per-node cover"):
        m.predict_contributions(fr)
    with pytest.raises(ValueError, match="per-node cover"):
        m.contrib_numpy(_X(m, fr))


# -- REST contributions route -------------------------------------------------


@pytest.fixture
def server(mesh8):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.MODELS.clear()
    rest.FRAMES.clear()


def _post_json(base, route, payload):
    req = urllib.request.Request(
        base + route, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=600) as r:
        return json.loads(r.read())


def _small_binomial(seed=3, n=300):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays({"x": x, "y": y})


def test_rest_contrib_two_request_batcher_smoke(server):
    """Tier-1 micro-batcher smoke on the contributions route: two
    concurrent requests coalesce, each gets ITS slice, and the
    per-model contrib counters land on /3/Stats."""
    fr = _small_binomial()
    m = GBM(ntrees=3, max_depth=2, seed=1).train(
        y="y", training_frame=fr)
    rest.MODELS["cgbm"] = m
    s0 = dict(rest.BATCHER.stats)
    results = [None, None]

    def hit(i):
        results[i] = _post_json(
            server, "/3/Predictions/models/cgbm/contributions",
            {"rows": [{"x": float(i)}, {"x": -float(i)}]})

    ts = [threading.Thread(target=hit, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(r is not None and r["rows"] == 2 for r in results)
    assert all(r["columns"] == ["x", "BiasTerm"] for r in results)
    s1 = rest.BATCHER.stats
    assert s1["requests"] >= s0["requests"] + 2
    for i, r in enumerate(results):
        want = m.contrib_numpy(
            np.array([[float(i)], [-float(i)]], np.float32))
        np.testing.assert_allclose(
            np.array(r["contributions"], np.float32), want,
            rtol=1e-5, atol=1e-6)
    with urllib.request.urlopen(server + "/3/Stats", timeout=60) as r:
        stats = json.loads(r.read())
    rec = stats["models"]["cgbm"]
    assert rec["contrib_requests"] >= 2
    assert rec["contrib_rows"] >= 4
    assert rec["contrib_batches"] >= 1


def test_rest_contrib_precondition_400s(server):
    """Error hygiene: multinomial / offset-trained / NaN-cover models
    answer the contributions route with a clean 400 + the retrain or
    re-export message — never a 500 traceback."""
    rng = np.random.default_rng(2)
    n = 240
    x = rng.normal(size=n).astype(np.float32)
    off = rng.normal(scale=0.1, size=n).astype(np.float32)
    y3 = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    y2 = np.where(x > 0, "p", "n")
    fr3 = h2o.Frame.from_arrays({"x": x, "y": y3})
    fr2 = h2o.Frame.from_arrays({"x": x, "off": off, "y": y2})
    rest.MODELS["multi"] = GBM(ntrees=2, max_depth=2, seed=0).train(
        y="y", training_frame=fr3)
    rest.MODELS["offm"] = GBM(ntrees=2, max_depth=2, seed=0).train(
        y="y", training_frame=fr2, offset_column="off")
    nocov = GBM(ntrees=2, max_depth=2, seed=0).train(
        y="y", training_frame=h2o.Frame.from_arrays(
            {"x": x, "y": y2}))
    nocov.trees = nocov.trees._replace(cover=np.full(
        np.asarray(nocov.trees.cover).shape, np.nan, np.float32))
    rest.MODELS["nocov"] = nocov

    def expect_400(key, needle, row):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_json(server,
                       f"/3/Predictions/models/{key}/contributions",
                       {"rows": [row]})
        assert e.value.code == 400, (key, e.value.code)
        body = e.value.read().decode()
        assert needle in body, (key, body)

    expect_400("multi", "binomial and regression", {"x": 1.0})
    expect_400("offm", "trained with an offset",
               {"x": 1.0, "off": 0.0})
    expect_400("nocov", "per-node cover", {"x": 1.0})
    # unknown model stays a 404, malformed payload a 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_json(server, "/3/Predictions/models/nope/contributions",
                   {"rows": [{"x": 1.0}]})
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_json(server,
                   "/3/Predictions/models/multi/contributions", {})
    assert e.value.code == 400


def test_registry_load_warms_contributions(server):
    """The operator push route pre-traces the contributions
    executables too: the first explain request after load adds zero
    scorer-cache misses (the warm-up contract covers both programs)."""
    import base64

    from h2o_kubernetes_tpu.mojo import export_mojo

    fr = _small_binomial(seed=5)
    m = GBM(ntrees=3, max_depth=2, seed=1).train(
        y="y", training_frame=fr)
    buf = io.BytesIO()
    export_mojo(m, buf)
    out = _post_json(server, "/3/ModelRegistry/load", {
        "model_id": "ten1",
        "artifact_b64": base64.b64encode(buf.getvalue()).decode(),
        "warm_buckets": [128]})
    assert out["contributions"] is True
    loaded = rest.MODELS["ten1"]
    c0 = model_scorer_counters(loaded)
    r = _post_json(server, "/3/Predictions/models/ten1/contributions",
                   {"rows": [{"x": 0.5}, {"x": -0.5}]})
    assert r["rows"] == 2
    c1 = model_scorer_counters(loaded)
    assert c1["misses"] == c0["misses"]
    with urllib.request.urlopen(server + "/3/Stats", timeout=60) as rr:
        stats = json.loads(rr.read())
    reg = stats["registry"]["ten1"]
    assert reg["contributions"] is True
    assert reg["warm_cache_misses"] == 0
