"""Multi-tenant serving (ISSUE 7 tentpole): the byte-budgeted,
cost-aware executable cache must (a) keep resident scorer bytes under
H2O_TPU_SCORER_CACHE_BYTES across 100+ tiny models, (b) make an
evict→promote round trip bitwise-identical AND a persistent-cache hit
(never a cold compile), (c) re-baseline warm_cache_misses across
eviction so /3/Stats never reports a promotion re-trace as an
SLO-violating miss, and (d) bound a tail model's latency while a hot
model floods the per-model-aware ScoreBatcher (fairness + SLO
classes) — with the unfair baseline (H2O_TPU_SCORE_FAIRNESS=0)
provably starving it.  The real-subprocess leg of the same contracts
is tools/chaos.py's tenant-storm drill."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest
from h2o_kubernetes_tpu.models import GBM
from h2o_kubernetes_tpu.models.base import (evict_scorer_cache,
                                            model_scorer_counters,
                                            scorer_cache_stats)
from h2o_kubernetes_tpu.operator import (ModelRegistry, ScorerPoolSpec,
                                         load_artifact)

pytestmark = pytest.mark.chaos


def _tiny_frame(n=400, seed=0, f=4):
    rng = np.random.default_rng(seed)
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
            for i in range(f)}
    cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late", "ontime")
    return h2o.Frame.from_arrays(cols)


def _tiny_artifact(seed=1, ntrees=2):
    fr = _tiny_frame(seed=seed)
    m = GBM(ntrees=ntrees, max_depth=2, seed=seed).train(
        y="y", training_frame=fr)
    reg = ModelRegistry(f"mem://multitenant_{seed}_{ntrees}")
    v = reg.publish(m, "t")
    return reg.fetch("t", v)


class _pcache:
    """Persistent XLA cache in a tmp dir with threshold 0, restored on
    exit — the evict→promote contract needs every serving compile
    persisted (the test_scheduler idiom)."""

    def __init__(self, tmp_path):
        self.dir = str(tmp_path)

    def __enter__(self):
        import jax
        from jax._src import compilation_cache as _cc

        self.jax, self._cc = jax, _cc
        self.prev_dir = jax.config.jax_compilation_cache_dir
        self.prev_min = \
            jax.config.jax_persistent_cache_min_compile_time_secs
        jax.config.update("jax_compilation_cache_dir", self.dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        _cc.reset_cache()   # is_cache_used latches: re-evaluate now
        return self

    def __exit__(self, *exc):
        self.jax.config.update("jax_compilation_cache_dir",
                               self.prev_dir)
        self.jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            self.prev_min)
        self._cc.reset_cache()


# ---------------------------------------------------------------------------
# Byte-budgeted cache: evict → promote
# ---------------------------------------------------------------------------


def test_evict_promote_bitwise_and_pcache_hit(mesh8, tmp_path):
    """Eviction drops executables + device arrays (host arrays stay);
    the next score re-promotes with BITWISE-identical output, counts a
    `promotion` (not a plain miss for the warm contract), and its
    compile is a persistent-cache HIT — the 'eviction costs a pcache
    hit, never a cold compile' tentpole claim."""
    from h2o_kubernetes_tpu.runtime.backend import (
        compile_watch_snapshot, start_compile_watch)

    start_compile_watch()
    blob = _tiny_artifact(seed=11, ntrees=3)
    with _pcache(tmp_path):
        sc = load_artifact(blob)
        rng = np.random.default_rng(3)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        out0 = sc.score_numpy(X)
        s0 = scorer_cache_stats()
        assert s0["resident"] >= 1

        assert evict_scorer_cache(sc) == 1
        assert "_scorer_cache" not in sc.__dict__
        assert "_flat_trees" not in sc.__dict__   # device arrays gone
        assert "_artifact_arrays" in sc.__dict__  # host state stays
        s1 = scorer_cache_stats()
        assert s1["evictions"] == s0["evictions"] + 1

        w0 = compile_watch_snapshot()
        out1 = sc.score_numpy(X)
        w1 = compile_watch_snapshot()
        s2 = scorer_cache_stats()
        # bitwise: same host arrays -> same constants -> same program
        np.testing.assert_array_equal(out0, out1)
        # the re-trace is accounted a promotion (and a miss: a miss IS
        # a new trace; promotions are the eviction-churn subset)
        assert s2["promotions"] == s1["promotions"] + 1
        assert s2["misses"] == s1["misses"] + 1
        ctr = model_scorer_counters(sc)
        assert ctr["promotions"] == 1
        # the promotion's backend compile came from the persistent
        # cache — zero cold compiles in the window
        assert w1["pcache_hits"] > w0["pcache_hits"]
        assert w1["pcache_misses"] == w0["pcache_misses"]


def test_byte_budget_enforced_under_100_models(mesh8, tmp_path,
                                               monkeypatch):
    """100+ tiny tenants under a small byte budget: resident bytes
    never exceed it, evictions happen, historical `models` keeps
    counting creations while `resident` tracks the live population,
    and every tenant stays scoreable (evicted ones re-promote)."""
    budget = 600_000
    monkeypatch.setenv("H2O_TPU_SCORER_CACHE_BYTES", str(budget))
    blob = _tiny_artifact(seed=5, ntrees=2)
    rng = np.random.default_rng(9)
    X = rng.normal(size=(32, 4)).astype(np.float32)
    with _pcache(tmp_path):
        s0 = scorer_cache_stats()
        tenants = [load_artifact(blob) for _ in range(104)]
        for i, t in enumerate(tenants):
            t.score_numpy(X)
            st = scorer_cache_stats()
            assert st["resident_bytes"] <= budget, \
                f"budget exceeded after tenant {i}: {st}"
        st = scorer_cache_stats()
        assert st["models"] >= s0["models"] + 104   # creations
        assert st["resident"] < 104                  # ...but evicted
        assert st["evictions"] > s0["evictions"]
        assert st["budget_bytes"] == budget
        # the first (coldest) tenant was evicted long ago — it must
        # still score, bitwise-equal to a fresh victim's output
        a = tenants[0].score_numpy(X)
        b = tenants[-1].score_numpy(X)
        np.testing.assert_array_equal(a, b)   # same artifact bytes
        assert scorer_cache_stats()["promotions"] > s0["promotions"]


def test_count_cap_still_works(mesh8, monkeypatch):
    """H2O_TPU_SCORER_CACHE_MAX survives as an optional count cap on
    top of the byte budget (rest of the semantics unchanged)."""
    monkeypatch.setenv("H2O_TPU_SCORER_CACHE_MAX", "1")
    monkeypatch.delenv("H2O_TPU_SCORER_CACHE_BYTES", raising=False)
    blob = _tiny_artifact(seed=21, ntrees=2)
    a, b = load_artifact(blob), load_artifact(blob)
    rng = np.random.default_rng(1)
    X = rng.normal(size=(16, 4)).astype(np.float32)
    ev0 = scorer_cache_stats()["evictions"]
    a.score_numpy(X)
    b.score_numpy(X)
    assert scorer_cache_stats()["evictions"] > ev0
    assert "_scorer_cache" not in a.__dict__
    assert scorer_cache_stats()["resident"] == 1


# ---------------------------------------------------------------------------
# Fairness: hot model must not starve the tail
# ---------------------------------------------------------------------------


class _SlowModel:
    """Stub with the score_numpy surface the batcher dispatches to —
    a fixed service delay stands in for device time."""

    algo = "stub"
    _serving_jit = True

    def __init__(self, delay=0.05, k=2):
        self.delay = delay
        self.k = k

    def score_numpy(self, X, offset=None):
        time.sleep(self.delay)
        return np.zeros((X.shape[0], self.k), dtype=np.float32)


def _flood(batcher, model, key, workers, rows, stop):
    """Closed-loop hot flood; returns the thread list + shed count."""
    shed = [0]

    def worker():
        X = np.zeros((rows, 4), dtype=np.float32)
        while not stop.is_set():
            try:
                batcher.submit(model, X, model_key=key,
                               slo="standard", timeout=5.0)
            except rest.QueueFullError:
                shed[0] += 1    # single >0 probe: races are harmless
                time.sleep(0.002)
            except Exception:
                pass

    ts = [threading.Thread(target=worker, daemon=True)
          for _ in range(workers)]
    for t in ts:
        t.start()
    return ts, shed


def test_fairness_bounds_tail_latency(monkeypatch):
    """Fairness ON: with a hot model flooding 12 closed-loop workers
    against an 8-slot queue, the hot model is capped at its SLO
    class's queue share, so every serial tail request is admitted
    (zero shed — structurally guaranteed: hot ≤ 4 + tail ≤ 1 < 8) and
    completes inside the interactive deadline."""
    monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_MAX", "8")
    monkeypatch.setenv("H2O_TPU_SCORE_FAIRNESS", "1")
    monkeypatch.setenv("H2O_TPU_SCORE_BATCH_US", "1000")
    batcher = rest.ScoreBatcher()
    hot, tail = _SlowModel(delay=0.03), _SlowModel(delay=0.001)
    stop = threading.Event()
    ts, hot_shed = _flood(batcher, hot, "hot", 12, 64, stop)
    try:
        time.sleep(0.2)     # flood established
        lat = []
        for _ in range(25):
            Xt = np.zeros((8, 4), dtype=np.float32)
            t0 = time.monotonic()
            out = batcher.submit(tail, Xt, model_key="tail",
                                 slo="interactive")
            lat.append(time.monotonic() - t0)
            assert out.shape == (8, 2)
        # interactive implicit deadline is 500ms: a single successful
        # submit PROVES in-deadline completion (a late one 504s), and
        # the p99-ish max here stays far inside it
        assert max(lat) < 0.5, f"tail latencies {sorted(lat)[-3:]}"
        # the hot model DID hit its own cap (fairness engaged)
        assert hot_shed[0] > 0
        assert batcher.stats["fairness_shed"] > 0
    finally:
        stop.set()
        batcher.stop(timeout=10)


def test_unfair_baseline_starves_tail(monkeypatch):
    """Fairness OFF (the measurable baseline): the same hot flood owns
    the whole queue, and the tail model's requests get shed and/or
    blow their deadline — the starvation the fairness knob exists to
    prevent."""
    monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_MAX", "8")
    monkeypatch.setenv("H2O_TPU_SCORE_FAIRNESS", "0")
    monkeypatch.setenv("H2O_TPU_SCORE_BATCH_US", "1000")
    batcher = rest.ScoreBatcher()
    hot, tail = _SlowModel(delay=0.03), _SlowModel(delay=0.001)
    stop = threading.Event()
    ts, _shed = _flood(batcher, hot, "hot", 12, 64, stop)
    try:
        time.sleep(0.2)
        misses = 0
        for _ in range(40):
            Xt = np.zeros((8, 4), dtype=np.float32)
            try:
                batcher.submit(tail, Xt, model_key="tail",
                               slo="interactive")
            except (rest.QueueFullError, rest._DeadlineExpired,
                    TimeoutError):
                misses += 1
        assert misses > 0, \
            "unfair baseline never starved the tail — the fairness " \
            "test above is not measuring anything"
    finally:
        stop.set()
        batcher.stop(timeout=10)


def test_fairness_cap_is_per_model_share(monkeypatch):
    """The admission cap applies per MODEL at the class share of the
    queue — a single model cannot occupy more slots than its share
    even with room left globally."""
    monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_MAX", "8")
    monkeypatch.setenv("H2O_TPU_SCORE_FAIRNESS", "1")
    # a huge window: pending jobs stay queued while we fill the share
    monkeypatch.setenv("H2O_TPU_SCORE_BATCH_US", "900000")
    batcher = rest.ScoreBatcher()
    m = _SlowModel(delay=0.0)
    errs = []
    done = []

    def submit_one():
        try:
            batcher.submit(m, np.zeros((4, 4), dtype=np.float32),
                           model_key="m", slo="standard", timeout=3.0)
        except rest.QueueFullError as e:
            errs.append(e)
        except Exception:
            pass
        done.append(1)

    ts = [threading.Thread(target=submit_one, daemon=True)
          for _ in range(6)]
    try:
        for t in ts:
            t.start()
        deadline = time.monotonic() + 5.0
        # standard share 0.5 of 8 = cap 4: of 6 concurrent submits at
        # most 4 may queue; the other 2 must shed fast
        while len(errs) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(errs) >= 2, \
            f"share cap never engaged (errs={len(errs)})"
        assert "fair share" in str(errs[0])
    finally:
        batcher.stop(timeout=10)
        batcher.reset()


# ---------------------------------------------------------------------------
# REST surface: SLO header, /3/Stats, warm-miss re-baseline, require
# ---------------------------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def tenant_server(mesh8):
    port = _free_port()
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.READINESS_GATES.clear()
    rest.REQUIRED_MODEL_IDS.clear()
    rest.REGISTRY_MODELS.clear()
    rest.MODEL_STATS.clear()
    rest.MODELS.clear()


def _load_tenant(base, model_id, blob=None, slo=None, seed=31):
    import base64

    blob = blob if blob is not None else _tiny_artifact(seed=seed)
    body = {"model_id": model_id, "warm_buckets": [128],
            "artifact_b64": base64.b64encode(blob).decode()}
    if slo is not None:
        body["slo"] = slo
    return _post(base, "/3/ModelRegistry/load", body)


def test_slo_header_and_per_model_stats(tenant_server):
    base = tenant_server
    code, out = _load_tenant(base, "tm", slo="interactive")
    assert code == 200 and out["slo"] == "interactive"
    rows = [{f"x{i}": 0.1 * i for i in range(4)}]
    # bogus SLO header: 400, not a silent downgrade
    code, out = _post(base, "/3/Predictions/models/tm",
                      {"rows": rows}, headers={"X-H2O-SLO": "turbo"})
    assert code == 400 and "turbo" in out["msg"]
    code, _ = _post(base, "/3/Predictions/models/tm", {"rows": rows},
                    headers={"X-H2O-SLO": "batch"})
    assert code == 200
    code, _ = _post(base, "/3/Predictions/models/tm", {"rows": rows})
    assert code == 200
    code, st = _get(base, "/3/Stats")
    assert code == 200
    # per-model serving counters + cache residency on ONE scrape
    assert st["models"]["tm"]["requests"] >= 2
    assert st["models"]["tm"]["batches"] >= 2
    assert st["models"]["tm"]["slo"] in ("interactive", "batch")
    for k in ("resident", "resident_bytes", "budget_bytes",
              "promotions"):
        assert k in st["scorer_cache"]
    assert "compiles" in st and "pcache_hits" in st["compiles"]
    assert st["fairness"] is True
    assert st["registry"]["tm"]["slo"] == "interactive"


def test_warm_misses_rebaseline_across_eviction(tenant_server):
    """The satellite fix: a warmed tenant reports warm_cache_misses=0;
    evicting it and scoring again (a promotion re-trace) must NOT
    flip that to 1 — only a genuinely unwarmed shape does."""
    base = tenant_server
    code, _ = _load_tenant(base, "wm", seed=37)
    assert code == 200
    rows = [{f"x{i}": 0.5 for i in range(4)}] * 8

    def wcm():
        _, st = _get(base, "/3/Stats")
        return st["registry"]["wm"]["warm_cache_misses"]

    code, _ = _post(base, "/3/Predictions/models/wm", {"rows": rows})
    assert code == 200
    assert wcm() == 0                      # warmed: zero misses
    evict_scorer_cache(rest.MODELS["wm"])  # budget pressure stand-in
    code, _ = _post(base, "/3/Predictions/models/wm", {"rows": rows})
    assert code == 200
    assert wcm() == 0, \
        "a promotion re-trace was reported as an SLO-violating miss"
    st = scorer_cache_stats()
    assert st["promotions"] >= 1
    # an UNWARMED shape (past the 128 bucket) is a real warm miss
    big = [{f"x{i}": 0.5 for i in range(4)}] * 200
    code, _ = _post(base, "/3/Predictions/models/wm", {"rows": big})
    assert code == 200
    assert wcm() == 1


def test_require_gates_readiness_until_all_loaded(tenant_server):
    """Multi-artifact readiness: POST /3/ModelRegistry/require pins
    the FULL tenant set; /readyz (with the pool gate) stays 503 after
    the first artifact lands and flips only when the last one is
    loaded + warmed."""
    base = tenant_server
    rest.install_pool_replica_gate()
    code, out = _post(base, "/3/ModelRegistry/require",
                      {"model_ids": ["a1", "a2"]})
    assert code == 200 and out["satisfied"] is False
    code, _ = _get(base, "/readyz")
    assert code == 503
    blob = _tiny_artifact(seed=41)
    assert _load_tenant(base, "a1", blob=blob)[0] == 200
    code, out = _get(base, "/readyz")
    assert code == 503, "readyz flipped with a required artifact " \
        f"still missing: {out}"
    assert any("a2" in r for r in out["reasons"])
    assert _load_tenant(base, "a2", blob=blob)[0] == 200
    assert _get(base, "/readyz")[0] == 200
    # malformed require: 400
    code, _ = _post(base, "/3/ModelRegistry/require",
                    {"model_ids": "a1"})
    assert code == 400


# ---------------------------------------------------------------------------
# Spec + Zipf plumbing
# ---------------------------------------------------------------------------


def test_spec_multi_artifact_validation():
    ok = ScorerPoolSpec(
        name="p", artifact="a", version=1, model_key="m",
        extra_artifacts=(("b", 1, "m2"), ("c", 2, "m3", "batch")))
    ok.validate()
    assert ok.all_artifacts() == [
        ("a", 1, "m", None), ("b", 1, "m2", None),
        ("c", 2, "m3", "batch")]
    with pytest.raises(ValueError, match="duplicate model_key"):
        ScorerPoolSpec(name="p", artifact="a", version=1,
                       model_key="m",
                       extra_artifacts=(("b", 1, "m"),)).validate()
    with pytest.raises(ValueError, match="extra_artifacts"):
        ScorerPoolSpec(name="p", artifact="a", version=1,
                       model_key="m",
                       extra_artifacts=(("b", 1),)).validate()
    with pytest.raises(ValueError, match="version"):
        ScorerPoolSpec(name="p", artifact="a", version=1,
                       model_key="m",
                       extra_artifacts=(("b", 0, "m2"),)).validate()
    # a typo'd SLO class must reject at APPLY time, not 400 on every
    # replica's artifact push
    with pytest.raises(ValueError, match="SLO class"):
        ScorerPoolSpec(name="p", artifact="a", version=1,
                       model_key="m", slo="interacive").validate()
    with pytest.raises(ValueError, match="SLO class"):
        ScorerPoolSpec(
            name="p", artifact="a", version=1, model_key="m",
            extra_artifacts=(("b", 1, "m2", "turbo"),)).validate()


def test_zipf_probs_shape():
    import sys as _sys
    import os as _os

    _sys.path.insert(0, _os.path.join(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
    from tools.datasets import zipf_probs

    p = zipf_probs(100, 1.1)
    assert p.shape == (100,)
    assert abs(p.sum() - 1.0) < 1e-12
    assert (np.diff(p) < 0).all()          # rank 1 hottest, monotone
    with pytest.raises(ValueError):
        zipf_probs(0)


# -- per-tenant rate limits (ISSUE 8 satellite; PR 7 "Remaining") -----------

def test_rate_limit_token_bucket(monkeypatch):
    """H2O_TPU_MODEL_RATE_LIMIT: a tenant past its sustained rate gets
    429 (QueueFullError with a refill-sized Retry-After) at admission
    — before taking a queue slot — while other tenants are untouched;
    the bucket refills over time."""
    monkeypatch.setenv("H2O_TPU_MODEL_RATE_LIMIT", "5")
    monkeypatch.setenv("H2O_TPU_SCORE_BATCH_US", "0")
    # freeze the bucket clock: on a loaded CI box 8 blocking submits
    # can take longer than one token's refill (200 ms at 5/s), which
    # would make exact burst-count assertions flaky
    frozen = [1000.0]
    monkeypatch.setattr(rest, "_bucket_now", lambda: frozen[0])
    rest.reset_rate_buckets()
    base_total = rest.STATS["rate_limited"]
    batcher = rest.ScoreBatcher()
    m = _SlowModel(delay=0.0)
    X = np.zeros((2, 4), dtype=np.float32)
    try:
        # burst capacity = max(1, rate) = 5 tokens: the 6th submit for
        # the same key must shed (clock frozen — zero refill)
        ok, limited = 0, 0
        retry_after = None
        for _ in range(8):
            try:
                batcher.submit(m, X, model_key="hot", slo="standard",
                               timeout=5.0)
                ok += 1
            except rest.QueueFullError as e:
                limited += 1
                retry_after = e.retry_after
        assert ok == 5 and limited == 3
        assert retry_after is not None and 0 < retry_after <= 0.25
        # another tenant's bucket is independent
        batcher.submit(m, X, model_key="cold", slo="standard",
                       timeout=5.0)
        # counters surfaced for /3/Stats
        assert rest.STATS["rate_limited"] - base_total == 3
        with rest._STATS_LOCK:
            assert rest.MODEL_STATS["hot"]["rate_limited"] == 3
            assert rest.MODEL_STATS["cold"].get("rate_limited", 0) == 0
        # refill: one token's worth of clock readmits the tenant
        frozen[0] += 0.25
        batcher.submit(m, X, model_key="hot", slo="standard",
                       timeout=5.0)
    finally:
        batcher.stop(timeout=10)
        rest.reset_rate_buckets()
        with rest._STATS_LOCK:
            rest.MODEL_STATS.pop("hot", None)
            rest.MODEL_STATS.pop("cold", None)


def test_rate_limit_off_by_default(monkeypatch):
    """Unset (or 0) = no limiting at all — the existing serving
    surface, chaos drills, and fairness tests see zero change."""
    monkeypatch.delenv("H2O_TPU_MODEL_RATE_LIMIT", raising=False)
    rest.reset_rate_buckets()
    batcher = rest.ScoreBatcher()
    m = _SlowModel(delay=0.0)
    X = np.zeros((1, 4), dtype=np.float32)
    try:
        for _ in range(30):
            batcher.submit(m, X, model_key="k", slo="standard",
                           timeout=5.0)
        assert not rest._RATE_BUCKETS       # bucket never materialized
    finally:
        batcher.stop(timeout=10)
        with rest._STATS_LOCK:
            rest.MODEL_STATS.pop("k", None)
