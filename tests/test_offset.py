"""offset_column for GLM and GBM.

Reference: hex/ModelBuilder offset_column + hex/glm/GLM offset handling
[U3] — the offset is a fixed per-row term added to the linear predictor
(GLM eta / GBM margin), supplied at train AND scoring time.

With no statsmodels in the image, parity comes from a hand-rolled numpy
IRLS reference (poisson) plus exact invariance properties:
 - gaussian: offset o  ==  fit of (y - o), predictions shifted back
 - any family: a CONSTANT offset c shifts only the intercept, by -c
 - bernoulli GBM: a constant offset is absorbed by the init prior, so
   predictions are unchanged
"""

import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu.models import DRF, GBM, GLM


def _poisson_irls_offset(X, y, off, n_iter=50):
    """Textbook Fisher scoring for poisson log-link with offset —
    the parity reference (statsmodels is not in this image)."""
    Xd = np.column_stack([X, np.ones(len(y))])
    beta = np.zeros(Xd.shape[1])
    beta[-1] = np.log(max(y.mean(), 1e-8))
    for _ in range(n_iter):
        eta = Xd @ beta + off
        mu = np.exp(np.clip(eta, -30, 30))
        z = eta + (y - mu) / mu - off
        W = mu
        G = Xd.T @ (W[:, None] * Xd)
        b = Xd.T @ (W * z)
        beta_new = np.linalg.solve(G, b)
        if np.max(np.abs(beta_new - beta)) < 1e-10:
            beta = beta_new
            break
        beta = beta_new
    return beta


def test_glm_poisson_offset_matches_numpy_irls(mesh8):
    rng = np.random.default_rng(0)
    n = 4000
    x = rng.normal(size=n)
    exposure = rng.uniform(0.5, 3.0, size=n)      # actuarial exposure
    off = np.log(exposure)
    y = rng.poisson(exposure * np.exp(0.6 * x + 0.4)).astype(float)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})
    m = GLM(family="poisson", lambda_=0.0).train(
        y="y", training_frame=fr, offset_column="off")
    want = _poisson_irls_offset(x[:, None], y, off)
    coef = m.coef()
    np.testing.assert_allclose(coef["x"], want[0], rtol=1e-4)
    np.testing.assert_allclose(coef["Intercept"], want[1], rtol=1e-4)
    # and the offset actually matters: coefficients differ from the
    # no-offset fit
    m0 = GLM(family="poisson", lambda_=0.0).train(
        y="y", training_frame=fr, ignored_columns=["off"])
    assert abs(m0.coef()["Intercept"] - coef["Intercept"]) > 1e-3


def test_glm_gaussian_offset_equals_shifted_response(mesh8):
    rng = np.random.default_rng(1)
    n = 3000
    x = rng.normal(size=n)
    off = rng.normal(size=n)
    y = 1.5 * x + 2.0 + off + rng.normal(scale=0.3, size=n)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y,
                            "y_shift": y - off})
    m = GLM(family="gaussian", lambda_=0.0).train(
        y="y", training_frame=fr, offset_column="off",
        ignored_columns=["y_shift"])
    m2 = GLM(family="gaussian", lambda_=0.0).train(
        y="y_shift", training_frame=fr, ignored_columns=["y", "off"])
    np.testing.assert_allclose(m.coef()["x"], m2.coef()["x"], rtol=1e-5)
    np.testing.assert_allclose(m.coef()["Intercept"],
                               m2.coef()["Intercept"], atol=1e-4)
    # predictions include the offset
    pred = m.predict_raw(fr)
    pred2 = m2.predict_raw(fr)
    np.testing.assert_allclose(pred, pred2 + off, atol=1e-3)


def test_glm_binomial_constant_offset_shifts_intercept(mesh8):
    rng = np.random.default_rng(2)
    n = 4000
    x = rng.normal(size=n)
    pr = 1 / (1 + np.exp(-(1.2 * x - 0.5)))
    y = np.array(["n", "p"])[(rng.uniform(size=n) < pr).astype(int)]
    c = 0.7
    fr = Frame.from_arrays({"x": x, "off": np.full(n, c), "y": y})
    m = GLM(family="binomial", lambda_=0.0).train(
        y="y", training_frame=fr, offset_column="off")
    m0 = GLM(family="binomial", lambda_=0.0).train(
        y="y", training_frame=fr, ignored_columns=["off"])
    np.testing.assert_allclose(m.coef()["x"], m0.coef()["x"], rtol=1e-4)
    np.testing.assert_allclose(m.coef()["Intercept"],
                               m0.coef()["Intercept"] - c, atol=1e-4)
    # null deviance uses the offset-aware intercept MLE: with a
    # constant offset it must equal the no-offset null deviance
    np.testing.assert_allclose(m.null_deviance, m0.null_deviance,
                               rtol=1e-5)


def test_glm_offset_validation(mesh8):
    rng = np.random.default_rng(3)
    n = 200
    fr = Frame.from_arrays({
        "x": rng.normal(size=n),
        "g": np.array(["a", "b"])[rng.integers(0, 2, size=n)],
        "y": rng.normal(size=n)})
    with pytest.raises(ValueError, match="not in frame"):
        GLM(family="gaussian").train(y="y", training_frame=fr,
                                     offset_column="nope")
    with pytest.raises(ValueError, match="numeric"):
        GLM(family="gaussian").train(y="y", training_frame=fr,
                                     offset_column="g")
    y3 = np.array(["a", "b", "c"])[rng.integers(0, 3, size=n)]
    fr3 = Frame.from_arrays({"x": rng.normal(size=n),
                             "off": rng.normal(size=n), "y": y3})
    with pytest.raises(ValueError, match="multinomial"):
        GLM(family="multinomial").train(y="y", training_frame=fr3,
                                        offset_column="off")


def test_gbm_gaussian_offset_equals_shifted_response(mesh8):
    rng = np.random.default_rng(4)
    n = 3000
    x = rng.normal(size=n)
    off = rng.normal(size=n)
    y = np.sin(2 * x) + off + rng.normal(scale=0.2, size=n)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y,
                            "y_shift": y - off})
    m = GBM(ntrees=10, max_depth=3, seed=7).train(
        y="y", training_frame=fr, offset_column="off",
        ignored_columns=["y_shift"])
    m2 = GBM(ntrees=10, max_depth=3, seed=7).train(
        y="y_shift", training_frame=fr, ignored_columns=["y", "off"])
    pred = m.predict_raw(fr)
    pred2 = m2.predict_raw(fr)
    np.testing.assert_allclose(pred, pred2 + off, atol=1e-4)


def test_gbm_bernoulli_constant_offset_absorbed_by_init(mesh8):
    rng = np.random.default_rng(5)
    n = 3000
    x = rng.normal(size=n)
    pr = 1 / (1 + np.exp(-1.5 * x))
    y = np.array(["n", "p"])[(rng.uniform(size=n) < pr).astype(int)]
    fr = Frame.from_arrays({"x": x, "off": np.full(n, 1.3), "y": y})
    m = GBM(ntrees=8, max_depth=3, seed=0).train(
        y="y", training_frame=fr, offset_column="off")
    m0 = GBM(ntrees=8, max_depth=3, seed=0).train(
        y="y", training_frame=fr, ignored_columns=["off"])
    # margin = init + c + trees == (init0) + trees: identical probs
    np.testing.assert_allclose(m.predict_raw(fr), m0.predict_raw(fr),
                               atol=2e-4)
    np.testing.assert_allclose(m.init_score + 1.3, m0.init_score,
                               atol=2e-4)


def test_gbm_poisson_offset_exposure(mesh8):
    rng = np.random.default_rng(6)
    n = 4000
    x = rng.normal(size=n)
    exposure = rng.uniform(0.5, 4.0, size=n)
    off = np.log(exposure)
    y = rng.poisson(exposure * np.exp(0.5 * x)).astype(float)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})
    m = GBM(ntrees=20, max_depth=3, distribution="poisson",
            seed=0).train(y="y", training_frame=fr, offset_column="off")
    m0 = GBM(ntrees=20, max_depth=3, distribution="poisson",
             seed=0).train(y="y", training_frame=fr,
                           ignored_columns=["off"])
    # offset model predicts counts including exposure; its per-exposure
    # rate error must beat the no-offset model's
    rate = np.exp(0.5 * x)
    err = np.abs(m.predict_raw(fr) / exposure - rate).mean()
    err0 = np.abs(m0.predict_raw(fr) / exposure - rate).mean()
    assert err < err0


def test_gbm_offset_scoring_requires_column(mesh8):
    rng = np.random.default_rng(7)
    n = 500
    x = rng.normal(size=n)
    off = rng.normal(size=n)
    y = x + off + rng.normal(scale=0.1, size=n)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})
    m = GBM(ntrees=3, max_depth=2).train(
        y="y", training_frame=fr, offset_column="off")
    bare = Frame.from_arrays({"x": x})
    with pytest.raises(ValueError, match="offset"):
        m.predict_raw(bare)
    with pytest.raises(ValueError, match="offset"):
        m.predict_contributions(fr)


def test_offset_unsupported_modes(mesh8):
    rng = np.random.default_rng(8)
    n = 300
    x = rng.normal(size=n)
    off = rng.normal(size=n)
    fr3 = Frame.from_arrays({
        "x": x, "off": off,
        "y": np.array(["a", "b", "c"])[rng.integers(0, 3, size=n)]})
    with pytest.raises(ValueError, match="multinomial"):
        GBM(ntrees=2).train(y="y", training_frame=fr3,
                            offset_column="off")
    frr = Frame.from_arrays({"x": x, "off": off,
                             "y": rng.normal(size=n)})
    with pytest.raises(ValueError, match="DRF"):
        DRF(ntrees=2).train(y="y", training_frame=frr,
                            offset_column="off")


def test_offset_mojo_and_xgboost_scoring(mesh8, tmp_path):
    """The exported artifact must score WITH the offset (it would
    otherwise silently shift every prediction), and the XGBoost model
    class must accept the offset kwarg at predict time."""
    from h2o_kubernetes_tpu.models import XGBoost
    from h2o_kubernetes_tpu.mojo import export_mojo, import_mojo

    rng = np.random.default_rng(10)
    n = 800
    x = rng.normal(size=n)
    off = rng.normal(scale=0.5, size=n)
    y = x + off + rng.normal(scale=0.1, size=n)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})
    for est in (GBM(ntrees=5, max_depth=3),
                GLM(family="gaussian", lambda_=0.0),
                XGBoost(ntrees=5, max_depth=3)):
        m = est.train(y="y", training_frame=fr, offset_column="off")
        want = m.predict_raw(fr)
        p = str(tmp_path / f"{m.algo}.mojo")
        export_mojo(m, p)
        mojo = import_mojo(p)
        got = mojo.predict({"x": x, "off": off})
        np.testing.assert_allclose(got, want, atol=1e-4)
        with pytest.raises(ValueError, match="offset"):
            mojo.predict({"x": x})


def test_offset_na_propagates_and_partial_plot(mesh8):
    rng = np.random.default_rng(11)
    n = 400
    x = rng.normal(size=n)
    off = rng.normal(size=n)
    y = x + off + rng.normal(scale=0.1, size=n)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})
    m = GBM(ntrees=4, max_depth=2).train(
        y="y", training_frame=fr, offset_column="off")
    off_na = off.copy()
    off_na[::10] = np.nan
    fr_na = Frame.from_arrays({"x": x, "off": off_na})
    pred = m.predict_raw(fr_na)
    # rows without a defined base margin have no defined prediction
    assert np.isnan(pred[::10]).all()
    assert not np.isnan(pred[1::10]).any()
    # partial_plot must score at the frame's offsets, consistent with
    # predict(): with offsets halved, the PD mean must shift too
    pd1 = m.partial_plot(fr, ["x"], nbins=5)[0]
    fr2 = Frame.from_arrays({"x": x, "off": off - 1.0})
    pd2 = m.partial_plot(fr2, ["x"], nbins=5)[0]
    m1 = np.asarray(pd1.vec("mean_response").as_float())[:5]
    m2 = np.asarray(pd2.vec("mean_response").as_float())[:5]
    np.testing.assert_allclose(m1 - 1.0, m2, atol=1e-4)


def test_dl_regression_offset(mesh8, tmp_path):
    """DL regression with offset: the net fits y - offset (exact for
    the shift-equivariant mse loss) and scoring adds it back; the
    softmax/autoencoder heads refuse it."""
    from h2o_kubernetes_tpu.models import DeepLearning
    from h2o_kubernetes_tpu.mojo import export_mojo, import_mojo

    rng = np.random.default_rng(12)
    n = 1200
    x = rng.normal(size=n)
    off = rng.normal(scale=2.0, size=n)     # big offsets: must matter
    y = np.sin(2 * x) + off + rng.normal(scale=0.1, size=n)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})
    # modest epochs + large train_samples_per_iteration: few collective
    # dispatches (every extra averaging round is another chance for the
    # known XLA:CPU rendezvous stall on a loaded 1-core box)
    kw = dict(hidden=(16,), epochs=8, mini_batch_size=64,
              train_samples_per_iteration=4 * n, seed=0)
    m = DeepLearning(**kw).train(
        y="y", training_frame=fr, offset_column="off")
    pred = m.predict_raw(fr)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    m0 = DeepLearning(**kw).train(
        y="y", training_frame=fr, ignored_columns=["off"])
    rmse0 = float(np.sqrt(np.mean((m0.predict_raw(fr) - y) ** 2)))
    # the offset carries sd=2.0 of the response; a net that can't see
    # it is stuck near that floor while the offset model fits the rest
    assert rmse < rmse0 * 0.7, (rmse, rmse0)
    # the mojo round-trips the offset too
    p = str(tmp_path / "dl.mojo")
    export_mojo(m, p)
    got = import_mojo(p).predict({"x": x, "off": off})
    np.testing.assert_allclose(got, pred, atol=1e-4)

    yb = np.array(["a", "b"])[(x > 0).astype(int)]
    frb = Frame.from_arrays({"x": x, "off": off, "y": yb})
    with pytest.raises(ValueError, match="regression"):
        DeepLearning(hidden=(8,), epochs=1).train(
            y="y", training_frame=frb, offset_column="off")


def test_special_columns_cannot_also_be_features(mesh8):
    rng = np.random.default_rng(0)
    n = 200
    fr = Frame.from_arrays({"x": rng.normal(size=n),
                            "off": rng.normal(size=n),
                            "fold": rng.integers(0, 3, size=n).astype(
                                np.float32),
                            "y": rng.normal(size=n)})
    with pytest.raises(ValueError, match="cannot also be features"):
        GBM(ntrees=2).train(y="y", training_frame=fr,
                            x=["x", "off"], offset_column="off")
    with pytest.raises(ValueError, match="cannot also be features"):
        GBM(ntrees=2).train(y="y", training_frame=fr, x=["x", "y"])
    # the CV fold column is set aside the same way
    with pytest.raises(ValueError, match="cannot also be features"):
        GBM(ntrees=2, nfolds=0, fold_column="fold").train(
            y="y", training_frame=fr, x=["x", "fold"])


def test_glm_offset_with_cv(mesh8):
    # the offset must ride through fold training and holdout scoring
    rng = np.random.default_rng(9)
    n = 1200
    x = rng.normal(size=n)
    off = rng.normal(scale=0.5, size=n)
    y = 1.0 * x + off + rng.normal(scale=0.3, size=n)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})
    m = GLM(family="gaussian", lambda_=0.0, nfolds=3).train(
        y="y", training_frame=fr, offset_column="off")
    assert m.cv is not None
    assert m.cross_validation_metrics()["r2"] > 0.5
