"""Reference-shaped dataset generators (tools/datasets.py) + the
capabilities they exercise: high-cardinality categorical binning and
the pyarrow CSV fast path's exact equivalence to the pure-Python
parser (BASELINE.json configs name airlines/HIGGS/MSLR shapes)."""

import os

import numpy as np
import pytest

from tools import datasets as D


def test_airlines_shape_and_nas():
    cols, domains = D.airlines_arrays(20_000, seed=1)
    assert len(cols) >= 25
    assert domains["IsDepDelayed"] == ["NO", "YES"]
    assert len(domains["Origin"]) == 300
    # NA injection present but bounded
    na = float(np.isnan(cols["DepTime"]).mean())
    assert 0.005 < na < 0.08
    # response is balanced-ish (a degenerate target would make every
    # AutoML model trivially equal)
    rate = float(np.nanmean(cols["IsDepDelayed"]))
    assert 0.3 < rate < 0.7


def test_mslr_shape():
    cols = D.mslr_arrays(20_000, seed=1, n_features=20)
    q = cols["qid"]
    assert (np.diff(q) >= 0).all()          # grouped + sorted
    _, counts = np.unique(q, return_counts=True)
    assert counts.mean() > 30               # real group sizes, not pairs
    hist = np.bincount(cols["rel"].astype(int), minlength=5)
    assert hist[0] > hist[1] > hist[2] > hist[3] >= hist[4] > 0


def test_airlines_frame_trains_gbm():
    from h2o_kubernetes_tpu.models import GBM

    fr = D.airlines_frame(4_000, seed=2)
    assert fr.vec("Origin").cardinality() == 300   # > n_bins: range-bin
    m = GBM(ntrees=3, max_depth=4, seed=1).train(
        y="IsDepDelayed", training_frame=fr)
    auc = float(m.model_performance(fr, y="IsDepDelayed")["auc"])
    assert auc > 0.7


def test_highcard_enum_binning_splits_levels():
    """Overflow enums bin by contiguous code ranges: codes far apart
    land in different bins, adjacent codes may share."""
    import jax.numpy as jnp

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models.tree.binning import (apply_bins,
                                                        fit_bins)

    card = 500
    codes = np.arange(card, dtype=np.float32)
    fr = h2o.Frame.from_arrays(
        {"c": codes}, domains={"c": [f"L{i}" for i in range(card)]})
    spec = fit_bins(fr, ["c"], n_bins=64)
    assert spec.is_enum == [False]          # overflow → numeric path
    binned = apply_bins(jnp.asarray(codes)[:, None],
                        spec.edges_matrix(),
                        jnp.asarray([False]), spec.na_bin)
    b = np.asarray(binned)[:, 0]
    assert b.min() == 0 and b.max() == 61   # fills the finite bins
    assert (np.diff(b) >= 0).all()          # order-preserving ranges
    # NA code (NaN after as_float) → NA bin
    binned_na = apply_bins(jnp.asarray([[np.nan]]),
                           spec.edges_matrix(),
                           jnp.asarray([False]), spec.na_bin)
    assert int(binned_na[0, 0]) == spec.na_bin


@pytest.mark.slow
def test_arrow_csv_matches_python_parser(tmp_path, monkeypatch):
    import h2o_kubernetes_tpu.frame.parse as P

    p = str(tmp_path / "air.csv")
    D.airlines_csv(p, 5_000, chunk=5_000)
    monkeypatch.delenv("H2O_TPU_ARROW_CSV", raising=False)
    fr = P.import_file(p)
    monkeypatch.setenv("H2O_TPU_ARROW_CSV", "0")
    fr2 = P.import_file(p)
    assert fr.names == fr2.names
    for n in fr.names:
        a, b = fr.vec(n), fr2.vec(n)
        assert a.domain == b.domain, n
        x = np.asarray(a.data)[: fr.nrows]
        y = np.asarray(b.data)[: fr2.nrows]
        assert np.allclose(x, y, equal_nan=True), n


def test_arrow_blank_line_before_header(tmp_path, monkeypatch):
    """A blank line before the header must not shift arrow's skip_rows
    (review finding: physical-line counting made the header a data
    row); both parsers must agree."""
    import h2o_kubernetes_tpu.frame.parse as P

    p = str(tmp_path / "b.csv")
    with open(p, "w") as f:
        f.write("\n  \na,b\n1,x\n2,y\n3,x\n")
    monkeypatch.delenv("H2O_TPU_ARROW_CSV", raising=False)
    fr = P.import_file(p)
    assert fr.nrows == 3 and fr.names == ["a", "b"]
    assert fr.vec("b").domain == ["x", "y"]
    monkeypatch.setenv("H2O_TPU_ARROW_CSV", "0")
    fr2 = P.import_file(p)
    assert fr2.nrows == 3 and fr2.names == fr.names
    np.testing.assert_allclose(
        np.asarray(fr.vec("a").data)[:3],
        np.asarray(fr2.vec("a").data)[:3])


def test_single_column_csv_uses_python_parser(tmp_path):
    """1-column frames are ineligible for the arrow path (whitespace-
    only lines would silently become NA rows there) — the pure-Python
    parser must handle them, skipping blank lines."""
    import h2o_kubernetes_tpu.frame.parse as P

    p = str(tmp_path / "one.csv")
    with open(p, "w") as f:
        f.write("name\nalpha\n \nbeta\n")
    fr = P.import_file(p)
    assert fr.nrows == 2
    assert fr.vec("name").domain == ["alpha", "beta"]
