"""Worker process for the 2-process DCN test (run by test_distributed.py).

Forms a 2-process JAX distributed cluster over localhost (the DCN path
of SURVEY.md §5.8 — the operator-injected H2O_TPU_* contract), builds a
GLOBAL 8-device mesh (2 hosts x 4 local CPU devices), and runs one
MRTask doall whose psum crosses the process boundary.
"""

import os
import re
import sys


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from h2o_kubernetes_tpu.runtime import (initialize_distributed,
                                            make_mesh, set_global_mesh)
    from h2o_kubernetes_tpu.runtime.mrtask import doall

    initialize_distributed(coordinator=f"localhost:{port}",
                           num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()      # global view
    assert len(jax.local_devices()) == 4

    mesh = make_mesh()                                 # 8-way ROWS
    set_global_mesh(mesh)
    n = 64
    data = np.arange(n, dtype=np.float32)
    sharding = NamedSharding(mesh, P("rows"))
    arr = jax.make_array_from_callback(
        (n,), sharding, lambda idx: data[idx])

    res = doall(lambda x: {"s": jnp.sum(x), "mx": jnp.max(x)},
                arr, reduce={"s": "sum", "mx": "max"}, mesh=mesh)
    s, mx = float(res["s"]), float(res["mx"])
    assert s == float(data.sum()), (s, data.sum())
    assert mx == float(n - 1), mx
    print(f"DCN_OK pid={pid} sum={s}", flush=True)


if __name__ == "__main__":
    main()
