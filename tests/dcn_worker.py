"""Worker process for the 2-process DCN tests (run by test_distributed.py).

Forms a 2-process JAX distributed cluster over localhost (the DCN path
of SURVEY.md §5.8 — the operator-injected H2O_TPU_* contract), builds a
GLOBAL 8-device mesh (2 hosts x 4 local CPU devices), and runs the
requested workload MODE:

  psum — one MRTask doall whose psum crosses the process boundary
  gbm  — a FULL fused-scan GBM train (sharded boost dispatches whose
         histogram psums ride the process boundary every level) +
         cross-process-identical AUC
  glm  — a full binomial IRLSM fit (distributed Gram psum per
         iteration) + coefficient recovery
  drop — process 1 exits after cluster formation; process 0 must
         detect the dead mesh via the heartbeat probe and fail fast
         with ClusterHealthError instead of training into a hang

The reference proves multi-node behavior with real multi-JVM localhost
clouds (SURVEY.md §4b); these are the same trick for the DCN runtime —
no mocked collectives, a real 2-process cluster per test.
"""

import os
import re
import sys


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])
    mode = sys.argv[3] if len(sys.argv) > 3 else "psum"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from h2o_kubernetes_tpu.runtime import (initialize_distributed,
                                            make_mesh, set_global_mesh)
    from h2o_kubernetes_tpu.runtime.mrtask import doall

    initialize_distributed(coordinator=f"localhost:{port}",
                           num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()      # global view
    assert len(jax.local_devices()) == 4

    mesh = make_mesh()                                 # 8-way ROWS
    set_global_mesh(mesh)

    if mode == "psum":
        n = 64
        data = np.arange(n, dtype=np.float32)
        sharding = NamedSharding(mesh, P("rows"))
        arr = jax.make_array_from_callback(
            (n,), sharding, lambda idx: data[idx])

        res = doall(lambda x: {"s": jnp.sum(x), "mx": jnp.max(x)},
                    arr, reduce={"s": "sum", "mx": "max"}, mesh=mesh)
        s, mx = float(res["s"]), float(res["mx"])
        assert s == float(data.sum()), (s, data.sum())
        assert mx == float(n - 1), mx
        print(f"DCN_OK pid={pid} sum={s}", flush=True)
        return

    # the model workloads build the SAME host data on every process
    # (single-controller-style SPMD: identical program, identical
    # inputs, device shards split by the global sharding)
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM, GLM

    rng = np.random.default_rng(0)
    n = 2000
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)

    if mode == "gbm":
        yb = np.where(1.2 * x1 - 0.8 * x2 +
                      rng.normal(scale=0.5, size=n) > 0, "p", "n")
        fr = h2o.Frame.from_arrays({"x1": x1, "x2": x2, "y": yb})
        m = GBM(ntrees=4, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        auc = float(m.scoring_history[-1]["train_auc"])
        assert auc > 0.85, auc
        # both processes must see the identical reduced model
        print(f"DCN_GBM_OK pid={pid} auc={auc:.6f}", flush=True)
        return

    if mode == "glm":
        pr = 1.0 / (1.0 + np.exp(-(0.8 * x1 - 1.5 * x2 + 0.3)))
        yb = np.where(rng.uniform(size=n) < pr, "p", "n")
        fr = h2o.Frame.from_arrays({"x1": x1, "x2": x2, "y": yb})
        m = GLM(family="binomial", lambda_=0.0).train(
            y="y", training_frame=fr)
        coef = m.coef()
        assert abs(coef["x1"] - 0.8) < 0.2, coef
        assert abs(coef["x2"] + 1.5) < 0.3, coef
        assert m.null_deviance > m.residual_deviance
        print(f"DCN_GLM_OK pid={pid} x1={coef['x1']:.6f}", flush=True)
        return

    if mode == "drop":
        from h2o_kubernetes_tpu.runtime import health

        # prove the cloud works first (one real cross-process train)
        yb = np.where(x1 > 0, "p", "n")
        fr = h2o.Frame.from_arrays({"x1": x1, "x2": x2, "y": yb})
        GBM(ntrees=2, max_depth=2, seed=1).train(
            y="y", training_frame=fr)
        if pid == 1:
            # die without goodbye — the locked cloud has lost a member
            print("DCN_DROP_EXITING pid=1", flush=True)
            os._exit(17)
        import time

        time.sleep(5.0)              # let process 1 actually die
        ok = health.heartbeat(timeout=20.0)
        assert not ok, "heartbeat still passing after a member died"
        try:
            GBM(ntrees=2, max_depth=2, seed=1).train(
                y="y", training_frame=fr)
            raise AssertionError("train on a dead mesh did not fail")
        except health.ClusterHealthError as e:
            print(f"DCN_DROP_OK pid=0 err={e}", flush=True)
        # exit without waiting on the dead runtime's shutdown barrier
        os._exit(0)

    raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
