"""Chip-native TreeSHAP kernel (ISSUE 17 tentpole): the Pallas
hand-placement of `flat_shap_tab` (`ops/shap_kernel.py`) must be
BITWISE-equal to the lowered-XLA reference on the rich fixtures (NAs,
grouped high-card enums, weights, DRF 1/T scaling, laplace
margin_scale), hold additivity, restore the XLA path bitwise under the
H2O_TPU_SHAP_KERNEL=0 kill switch, survive evict→promote bitwise with
the kernel resident, and serve registry artifacts through the kernel
bitwise vs the training-side model.  On CPU the kernel runs in
INTERPRET mode (`interpret=jax.default_backend() != "tpu"`), so these
are semantics pins; real-Mosaic lowering is the kernel gate's
`shap_kernel_parity` job on chip."""

import io
import pickle

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import DRF, GBM
from h2o_kubernetes_tpu.models.base import (evict_scorer_cache,
                                            model_scorer_counters)
from h2o_kubernetes_tpu.ops.shap_kernel import (flat_shap_tab_kernel,
                                                kernel_fits,
                                                resolve_impl)


def _rich_frame(n=500, seed=7, nlevels=60):
    """Same matrix as tests/test_contrib.py: numeric-with-NA +
    low-card enum + HIGH-card enum (grouped code ranges at nbins=64)
    + weights + binary response."""
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x0[::17] = np.nan
    x1 = rng.exponential(2.0, size=n).astype(np.float32)
    g = np.array([f"L{i}" for i in range(nlevels)])[
        rng.integers(0, nlevels, n)]
    c = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    y = np.where(np.nan_to_num(x0) + (c == "a")
                 + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays(
        {"x0": x0, "x1": x1, "g": g, "c": c, "w": w, "y": y})


def _X(m, fr) -> np.ndarray:
    return np.asarray(m._design_matrix(fr))[: fr.nrows]


def _leg(m, X, env, monkeypatch):
    """contrib_numpy with the impl FORCED on a fresh pickle copy —
    the env knob is read at trace time and the scorer cache keys on
    shape, not impl, so a warm executable would shadow the flip."""
    mc = pickle.loads(pickle.dumps(m))
    monkeypatch.setenv("H2O_TPU_SHAP_KERNEL", env)
    try:
        return mc.contrib_numpy(X)
    finally:
        monkeypatch.delenv("H2O_TPU_SHAP_KERNEL", raising=False)


def test_kernel_groups_bitwise_vs_xla_reference(mesh8):
    """Per virtual-tree-group: the Pallas kernel output is BITWISE
    the XLA `flat_shap_tab` output on the rich fixture, at a pow2
    serving shape."""
    import jax.numpy as jnp

    from h2o_kubernetes_tpu.models.tree.shap import flat_shap_tab

    fr = _rich_frame()
    m = GBM(ntrees=8, max_depth=4, nbins=64, seed=1).train(
        y="y", training_frame=fr, weights_column="w")
    groups, ctabs = m._contrib_prepare()
    em = m._contrib_enum_mask()
    Xp = jnp.asarray(_X(m, fr)[:256])
    ngr = 0
    for g, ct in zip(groups, ctabs):
        if ct is None or not kernel_fits(g, ct, 256):
            continue
        ngr += 1
        want = np.asarray(flat_shap_tab(g, ct, Xp, em))
        got = np.asarray(flat_shap_tab_kernel(g, ct, Xp, em))
        assert np.array_equal(want, got)
    assert ngr > 0      # the fixture must actually exercise the kernel


def test_kill_switch_restores_xla_bitwise(mesh8, monkeypatch):
    """=0 (kill switch) equals BOTH the untouched default path on CPU
    and the forced-kernel leg bitwise — flipping the knob never
    changes served bytes."""
    fr = _rich_frame(n=400, seed=13)
    m = GBM(ntrees=6, max_depth=4, nbins=64, seed=2).train(
        y="y", training_frame=fr, weights_column="w")
    X = _X(m, fr)
    base = m.contrib_numpy(X)       # auto -> xla on cpu
    off = _leg(m, X, "0", monkeypatch)
    on = _leg(m, X, "1", monkeypatch)
    assert np.array_equal(base, off)
    assert np.array_equal(off, on)


@pytest.mark.parametrize("algo", ["gbm", "drf", "laplace"])
def test_kernel_end_to_end_rich_fixtures(mesh8, monkeypatch, algo):
    """Forced-kernel serving matches the XLA leg bitwise and holds
    additivity on every rich fixture class: weighted binomial GBM,
    DRF (1/T scaling), laplace (margin_scale)."""
    import jax.numpy as jnp

    if algo == "gbm":
        fr = _rich_frame(n=400, seed=17)
        m = GBM(ntrees=6, max_depth=4, nbins=64, seed=1).train(
            y="y", training_frame=fr, weights_column="w")
    elif algo == "drf":
        fr = _rich_frame(n=400, seed=11)
        m = DRF(ntrees=5, max_depth=3, seed=5).train(
            y="y", training_frame=fr)
    else:
        rng = np.random.default_rng(3)
        n = 400
        x = rng.normal(size=n).astype(np.float32)
        x[::11] = np.nan
        yv = (2.0 * np.nan_to_num(x)
              + rng.normal(scale=0.3, size=n)).astype(np.float32)
        fr = h2o.Frame.from_arrays({"x": x, "y": yv})
        m = GBM(ntrees=5, max_depth=3, distribution="laplace",
                seed=2).train(y="y", training_frame=fr)
        assert m.margin_scale != 1.0
    X = _X(m, fr)
    on = _leg(m, X, "1", monkeypatch)
    off = _leg(m, X, "0", monkeypatch)
    assert np.array_equal(on, off)
    margins = np.asarray(m._margins(jnp.asarray(X)))[: fr.nrows]
    np.testing.assert_allclose(on.sum(axis=1), margins,
                               rtol=1e-4, atol=1e-4)


def test_evict_promote_bitwise_with_kernel_resident(mesh8,
                                                    monkeypatch):
    """The kernel executables ride the existing serving machinery:
    evicting a kernel-resident model and re-scoring re-promotes
    (persistent XLA cache) and reproduces the SAME bytes."""
    monkeypatch.setenv("H2O_TPU_SHAP_KERNEL", "1")
    fr = _rich_frame(n=300, seed=19)
    m = GBM(ntrees=4, max_depth=3, nbins=64, seed=3).train(
        y="y", training_frame=fr)
    X = _X(m, fr)
    phi1 = m.contrib_numpy(X)
    ctr0 = model_scorer_counters(m)
    evict_scorer_cache(m)
    assert "_shap_tables" not in m.__dict__   # device tables dropped
    assert "_shap_tables_np" in m.__dict__    # host tables survive
    phi2 = m.contrib_numpy(X)
    assert np.array_equal(phi1, phi2)
    ctr1 = model_scorer_counters(m)
    assert ctr1["promotions"] > ctr0["promotions"]


def test_warm_up_covers_kernel_program(mesh8, monkeypatch):
    """warm_up(contributions=True) pre-traces the KERNEL program too:
    warm serving adds zero scorer-cache misses with the kernel on."""
    monkeypatch.setenv("H2O_TPU_SHAP_KERNEL", "1")
    fr = _rich_frame(n=300, seed=23)
    m = GBM(ntrees=3, max_depth=3, nbins=64, seed=3).train(
        y="y", training_frame=fr)
    X = _X(m, fr)
    m.warm_up([256], contributions=True)
    c0 = model_scorer_counters(m)
    m.contrib_numpy(X[:50])
    m.contrib_numpy(X[:200])
    c1 = model_scorer_counters(m)
    assert c1["misses"] == c0["misses"]


def test_registry_scorer_serves_through_kernel_bitwise(mesh8,
                                                       monkeypatch):
    """A registry-loaded FlatTreeScorer under the kernel serves
    contributions BITWISE-identical to the training-side model (same
    tables -> same program), and to the XLA leg."""
    from h2o_kubernetes_tpu.mojo import export_mojo
    from h2o_kubernetes_tpu.operator.registry import load_artifact

    fr = _rich_frame(n=300, seed=29)
    m = GBM(ntrees=4, max_depth=3, nbins=64, seed=5).train(
        y="y", training_frame=fr)
    X = _X(m, fr)
    want_xla = _leg(m, X, "0", monkeypatch)
    monkeypatch.setenv("H2O_TPU_SHAP_KERNEL", "1")
    want = pickle.loads(pickle.dumps(m)).contrib_numpy(X)
    buf = io.BytesIO()
    export_mojo(m, buf)
    fts = load_artifact(buf.getvalue())
    got = fts.contrib_numpy(X)
    assert np.array_equal(got, want)
    assert np.array_equal(got, want_xla)


def test_resolve_impl_and_eligibility():
    """Knob hygiene: junk env raises (a typo must not silently demote
    the kernel); ineligible shapes fall back instead of tracing."""
    import jax.numpy as jnp

    import os
    assert resolve_impl("pallas") == "pallas"
    assert resolve_impl("xla") == "xla"
    os.environ["H2O_TPU_SHAP_KERNEL"] = "1"
    try:
        assert resolve_impl() == "pallas"
        os.environ["H2O_TPU_SHAP_KERNEL"] = "bogus"
        with pytest.raises(ValueError, match="H2O_TPU_SHAP_KERNEL"):
            resolve_impl()
    finally:
        os.environ.pop("H2O_TPU_SHAP_KERNEL", None)
    with pytest.raises(ValueError):
        resolve_impl("segment")
    # eligibility: no pattern table / non-pow2 / tiny batches say no
    class G:
        feat = jnp.zeros((1, 4, 3), jnp.int32)

    ct = jnp.zeros((1, 4, 3, 8), jnp.float32)
    assert not kernel_fits(G, None)
    assert not kernel_fits(G, ct, 100)       # non-pow2
    assert not kernel_fits(G, ct, 64)        # < serving min batch
    assert kernel_fits(G, ct, 256)
