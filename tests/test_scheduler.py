"""Pipelined AutoML executor tests (runtime/scheduler.py + the wiring
in automl.py / models/cv.py / models/gbm.py / models/tree/binning.py):

- HostStream ordering: tasks apply in sequence order whatever order
  they complete/arrive; skip() fills gaps; a gap with no skip is a
  named TimeoutError at drain, never a hang; task errors are captured.
- Device-token exclusivity: two threads can never hold it at once.
- Compile-ahead cache-hit accounting: AOT pre-lowering a config's
  boost executables makes the real train() hit the persistent XLA
  cache (fills cold, warm no-op on resubmission).
- Fused first-dispatch binning: bitwise parity (edges + codes) with
  the two-dispatch fit_bins -> Frame.binned path, and the kill switch.
- Pipelined vs sequential AutoML determinism: identical leaderboard
  ranking, metrics, and resume manifest for the same seed/plan; a
  mid-pipeline ``automl.step`` fault fails the job terminally with the
  finished steps' manifest entries written, and the rerun resumes.
"""

import json
import os
import tempfile
import threading
import time

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.runtime import scheduler as sched

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# HostStream / device token / CompileStream units (no device work)
# ---------------------------------------------------------------------------

class TestHostStream:
    def test_out_of_order_submission_applies_in_seq_order(self):
        hs = sched.HostStream(name="t-host-ooo", max_pending=8)
        applied = []
        done = threading.Event()

        def mk(i, sleep=0.0):
            def fn():
                if sleep:
                    time.sleep(sleep)
                applied.append(i)
                if i == 3:
                    done.set()
            return fn

        # seq 1 and 3 arrive BEFORE 0 and 2 — application order must
        # still be 0,1,2,3 (the leaderboard/manifest ordering contract)
        hs.submit(1, mk(1))
        hs.submit(3, mk(3))
        time.sleep(0.1)
        assert applied == []          # held back: seq 0 not in yet
        hs.submit(0, mk(0, sleep=0.05))
        hs.submit(2, mk(2))
        assert done.wait(timeout=10)
        assert applied == [0, 1, 2, 3]
        assert hs.stop(timeout=10)

    def test_skip_fills_gaps(self):
        hs = sched.HostStream(name="t-host-skip", max_pending=8)
        applied = []
        hs.submit(2, lambda: applied.append(2))
        hs.skip(0)
        hs.skip(1)
        assert hs.drain(timeout=10) == []
        assert applied == [2]
        assert hs.stats["skipped"] == 2
        assert hs.stop(timeout=10)

    def test_drain_names_the_wedge(self):
        hs = sched.HostStream(name="t-host-wedge", max_pending=8)
        hs.submit(1, lambda: None)    # seq 0 never submitted or skipped
        with pytest.raises(TimeoutError, match="pending=\\[1\\]"):
            hs.drain(timeout=0.5)
        hs.skip(0)                    # unwedge, then clean shutdown
        assert hs.drain(timeout=10) == []
        assert hs.stop(timeout=10)

    def test_full_queue_of_held_back_seqs_admits_the_gap_filler(self):
        """Regression: a queue full of tasks all held back by a missing
        lower seq must ADMIT that seq's submit (blocking it would
        deadlock the producer against its own backlog)."""
        hs = sched.HostStream(name="t-host-gap", max_pending=2)
        applied = []
        for s in (1, 2):              # fills the bound; worker starves
            hs.submit(s, lambda s=s: applied.append(s))
        time.sleep(0.1)
        hs.submit(0, lambda: applied.append(0))   # must not block
        assert hs.drain(timeout=10) == []
        assert applied == [0, 1, 2]
        assert hs.stop(timeout=10)

    def test_errors_captured_not_raised(self):
        hs = sched.HostStream(name="t-host-err", max_pending=8)
        applied = []

        def boom():
            raise RuntimeError("completion failed")

        hs.submit(0, boom, label="step0")
        hs.submit(1, lambda: applied.append(1))
        errs = hs.drain(timeout=10)
        # the failed task did not stall the stream, and the error is
        # attributed to its seq/label
        assert applied == [1]
        assert len(errs) == 1
        assert errs[0][0] == 0 and errs[0][1] == "step0"
        assert isinstance(errs[0][2], RuntimeError)
        assert hs.stop(timeout=10)


class TestDeviceToken:
    def test_token_exclusivity(self):
        ex = sched.PipelinedExecutor(compile_ahead=0)
        active = []
        overlap = []

        def worker(i):
            with ex.device(f"w{i}"):
                active.append(i)
                if len(active) > 1:
                    overlap.append(tuple(active))
                time.sleep(0.05)
                active.remove(i)

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert overlap == []
        st = ex.stats()
        assert st["device_steps"] == 4
        assert st["device_busy_s"] >= 4 * 0.05 * 0.9
        ex.shutdown()

    def test_queue_depth_backpressure_and_drop(self):
        # host stream blocks submit at the bound (the bound covers the
        # QUEUED backlog; an in-flight task has already left the queue)
        hs = sched.HostStream(name="t-host-bp", max_pending=2)
        release = threading.Event()
        hs.submit(0, release.wait)     # in-flight, holds the worker
        time.sleep(0.1)
        hs.submit(1, lambda: None)
        hs.submit(2, lambda: None)     # queue now at the bound
        t0 = time.monotonic()

        def unblock():
            time.sleep(0.3)
            release.set()

        threading.Thread(target=unblock).start()
        hs.submit(3, lambda: None)    # must block until a slot frees
        assert time.monotonic() - t0 >= 0.2
        assert hs.drain(timeout=10) == []
        assert hs.stop(timeout=10)


# ---------------------------------------------------------------------------
# data helpers
# ---------------------------------------------------------------------------

def _frame(n=240, seed=7):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    y = np.where(x0 + 0.5 * x1 + rng.normal(scale=0.5, size=n) > 0,
                 "p", "n")
    return h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": y})


# ---------------------------------------------------------------------------
# fused first-dispatch binning parity
# ---------------------------------------------------------------------------

class TestFusedBinning:
    def test_bitwise_parity_with_two_dispatch_path(self, mesh8):
        from h2o_kubernetes_tpu.models.tree.binning import (
            fit_bins, fused_fit_bins)

        rng = np.random.default_rng(3)
        n = 2000
        cols = {f"f{i}": rng.normal(size=n).astype(np.float32)
                for i in range(4)}
        cols["f0"][::13] = np.nan                       # NAs
        cols["c"] = rng.choice(["a", "b", "c"], size=n)  # enum
        # high-cardinality enum: the range-grouping edge path
        cols["hc"] = np.array(
            [f"L{v:03d}" for v in rng.integers(0, 200, size=n)])
        fr = h2o.Frame.from_arrays(cols)
        names = list(cols)

        spec_c = fit_bins(fr, names, 64)
        binned_c = np.asarray(fr.binned(spec_c))
        spec_f, binned_f = fused_fit_bins(fr, names, 64)
        assert np.array_equal(np.asarray(spec_c.edges_matrix()),
                              np.asarray(spec_f.edges_matrix()))
        assert np.array_equal(binned_c, np.asarray(binned_f))
        assert spec_c.is_enum == spec_f.is_enum

        # the fit-key cache: a second fused call is a pure hit
        spec_f2, binned_f2 = fused_fit_bins(fr, names, 64)
        assert spec_f2 is spec_f and binned_f2 is binned_f
        # mutation invalidates via the frame version counter
        from h2o_kubernetes_tpu.frame import Vec

        fr["extra"] = Vec.from_numpy(np.zeros(n, dtype=np.float32),
                                     "extra")
        spec_f3, _ = fused_fit_bins(fr, names, 64)
        assert spec_f3 is not spec_f

    def test_kill_switch_trains_identically(self, mesh8):
        from h2o_kubernetes_tpu.models import GBM

        fr = _frame(300, seed=5)
        m_fused = GBM(ntrees=4, max_depth=3, seed=0).train(
            y="y", training_frame=fr)
        os.environ["H2O_TPU_FUSED_BINNING"] = "0"
        try:
            m_classic = GBM(ntrees=4, max_depth=3, seed=0).train(
                y="y", training_frame=fr)
        finally:
            os.environ.pop("H2O_TPU_FUSED_BINNING", None)
        assert np.array_equal(np.asarray(m_fused.trees.value),
                              np.asarray(m_classic.trees.value))


# ---------------------------------------------------------------------------
# compile-ahead: cache-hit accounting against the real train path
# ---------------------------------------------------------------------------

class TestCompileAhead:
    def test_compile_ahead_covers_train(self, mesh8, tmp_path):
        """The drift pin: an AOT pre-lowered config's boost programs
        must be persistent-cache HITS when train() dispatches them.
        Control (no AOT) shows misses; the prepared config shows hits
        and strictly fewer misses; a warm resubmission is a no-op."""
        import jax

        from h2o_kubernetes_tpu.models import GBM
        from h2o_kubernetes_tpu.runtime.backend import (
            compile_watch_snapshot, start_compile_watch)

        from jax._src import compilation_cache as _cc

        start_compile_watch()
        prev_dir = jax.config.jax_compilation_cache_dir
        prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        # is_cache_used latches once per process — re-evaluate it with
        # the cache dir now set (and again on restore)
        _cc.reset_cache()
        ident = threading.get_ident()
        fr = _frame(2048, seed=3)

        def train(depth):
            return GBM(ntrees=4, max_depth=depth, seed=1, nfolds=2,
                       fold_assignment="modulo").train(
                y="y", training_frame=fr)

        try:
            train(3)                 # warm every aux program/shape
            b = compile_watch_snapshot(ident)
            train(4)                 # control: fresh depth, no AOT
            a = compile_watch_snapshot(ident)
            ctrl_miss = a["thread_pcache_misses"] \
                - b["thread_pcache_misses"]
            assert ctrl_miss >= 2    # boost @ full + fold shape

            est = GBM(ntrees=4, max_depth=5, seed=1, nfolds=2,
                      fold_assignment="modulo")
            thunks = est.compile_ahead_lowerings("y", fr)
            assert len(thunks) >= 2
            cs = sched.CompileStream(name="t-compile", max_queue=4)
            assert cs.submit("k5", lambda: thunks)
            assert cs.wait_idle(timeout=300)
            assert cs.stats["programs"] == len(thunks)
            assert cs.stats["fills"] >= 2      # cold: cache fills
            b = compile_watch_snapshot(ident)
            train(5)                 # the prepared config
            a = compile_watch_snapshot(ident)
            hits = a["thread_pcache_hits"] - b["thread_pcache_hits"]
            misses = a["thread_pcache_misses"] \
                - b["thread_pcache_misses"]
            assert hits >= 2, \
                f"pre-lowered boost programs missed (hits={hits})"
            assert misses < ctrl_miss

            # warm resubmission: the promised no-op (hit accounting)
            thunks2 = GBM(ntrees=4, max_depth=5, seed=1, nfolds=2,
                          fold_assignment="modulo"
                          ).compile_ahead_lowerings("y", fr)
            assert cs.submit("k5b", lambda: thunks2)
            assert cs.wait_idle(timeout=300)
            assert cs.stats["warm"] >= len(thunks2)
            assert cs.stop(timeout=30)
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", prev_min)
            _cc.reset_cache()

    def test_unsupported_and_dedupe_accounting(self, mesh8):
        cs = sched.CompileStream(name="t-compile-acct", max_queue=2)
        cs.mark_unsupported()
        assert cs.submit("a", lambda: [])
        assert not cs.submit("a", lambda: [])      # deduped
        assert cs.wait_idle(timeout=30)
        assert cs.stats["unsupported"] == 1
        assert cs.stats["deduped"] == 1
        # builder errors are counted, never raised
        assert cs.submit("b", lambda: 1 / 0)
        assert cs.wait_idle(timeout=30)
        assert cs.stats["errors"] == 1
        assert cs.stop(timeout=30)


# ---------------------------------------------------------------------------
# pipelined vs sequential AutoML: determinism + fault/resume round-trip
# ---------------------------------------------------------------------------

def _strip_walltime(rows):
    return [{k: v for k, v in r.items() if k != "training_time_s"}
            for r in rows]


def _norm_manifest(man):
    return {k: {"fam": v["fam"],
                "metrics": {mk: mv for mk, mv in v["metrics"].items()
                            if mk != "training_time_s"}}
            for k, v in man.items()}


def _run_automl(pipeline: bool, fr, ckpt=None, **kw):
    from h2o_kubernetes_tpu.automl import AutoML

    os.environ["H2O_TPU_AUTOML_PIPELINE"] = "1" if pipeline else "0"
    try:
        aml = AutoML(verbosity=None, checkpoint_dir=ckpt, **kw)
        aml.train(y="y", training_frame=fr)
        return aml
    finally:
        os.environ.pop("H2O_TPU_AUTOML_PIPELINE", None)


def _scheduler_threads():
    return [t.name for t in threading.enumerate() if t.is_alive() and
            (t.name.startswith("h2o-automl-") or
             t.name.startswith("h2o-cv-"))]


class TestPipelinedAutoML:
    def test_pipelined_matches_sequential(self, mesh8):
        """The ordering contract end to end: identical leaderboard
        (ids, ranking, every metric digit) and identical manifest for
        the same seed/plan — pipelined vs H2O_TPU_AUTOML_PIPELINE=0."""
        fr = _frame(240, seed=9)
        kw = dict(max_models=2, nfolds=2, seed=5,
                  include_algos=["glm", "gbm"], project_name="detm")
        with tempfile.TemporaryDirectory() as d1, \
                tempfile.TemporaryDirectory() as d2:
            a_pipe = _run_automl(True, fr, ckpt=d1, **kw)
            a_ser = _run_automl(False, fr, ckpt=d2, **kw)
            assert _strip_walltime(a_pipe.leaderboard.as_list()) == \
                _strip_walltime(a_ser.leaderboard.as_list())
            m1 = json.load(open(os.path.join(
                d1, "automl_manifest.json")))
            m2 = json.load(open(os.path.join(
                d2, "automl_manifest.json")))
            assert list(m1) == list(m2)          # insertion order too
            assert _norm_manifest(m1) == _norm_manifest(m2)
        assert a_pipe.job.status == "DONE"
        assert a_pipe.scheduler_stats is not None
        assert a_pipe.scheduler_stats["device_steps"] == 2
        assert a_pipe.scheduler_stats["host_applied"] == 2
        assert a_ser.scheduler_stats is None     # serial path: no
        assert _scheduler_threads() == []        # executor at all

    def test_mid_pipeline_fault_resumes(self, mesh8):
        """An automl.step device error mid-pipeline: job FAILED
        terminally, the finished step's manifest entry landed BEFORE
        the failure propagated (host stream drained on the error
        path), no scheduler thread left behind — and the rerun with
        the same checkpoint_dir resumes instead of retraining."""
        from h2o_kubernetes_tpu.runtime import faults, health

        fr = _frame(200, seed=12)
        kw = dict(max_models=2, nfolds=2, seed=11,
                  include_algos=["glm", "gbm"], project_name="pfault")
        with tempfile.TemporaryDirectory() as ckpt:
            health.reset()
            with faults.inject("automl.step:device_error@1"):
                with pytest.raises(health.ClusterHealthError):
                    _run_automl(True, fr, ckpt=ckpt, **kw)
            man = json.load(open(os.path.join(
                ckpt, "automl_manifest.json")))
            assert len(man) == 1         # GLM_1 finished + persisted
            assert _scheduler_threads() == []
            health.reset()
            a2 = _run_automl(True, fr, ckpt=ckpt, **kw)
            assert any("resumed from checkpoint" in m
                       for _, m in a2.event_log)
            assert len(a2.leaderboard.rows) == 2
            assert a2.job.status == "DONE"
