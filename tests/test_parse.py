"""CSV ingest + Frame row-op tests (reference: water/parser ParseDataset
type inference + FrameSplitter; SURVEY.md §2b C8)."""

import gzip

import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame, import_file, parse_setup
from h2o_kubernetes_tpu.frame import NA_ENUM

CSV = """id,age,income,city,signup
1,34,55000.5,austin,2021-03-04
2,41,NA,boston,2021-05-12
3,,72100,austin,2022-01-30
4,29,48000,chicago,2021-11-02
5,50,91000,?,2020-07-19
"""


@pytest.fixture
def csvfile(tmp_path, mesh8):
    p = tmp_path / "data.csv"
    p.write_text(CSV)
    return str(p)


def test_parse_setup_inference(csvfile):
    s = parse_setup(csvfile)
    assert s["sep"] == ","
    assert s["header"] is True
    assert s["names"] == ["id", "age", "income", "city", "signup"]
    assert s["types"] == ["numeric", "numeric", "numeric", "enum", "time"]


def test_import_file_values(csvfile):
    fr = import_file(csvfile)
    assert fr.shape == (5, 5)
    np.testing.assert_allclose(fr["id"].to_numpy(), [1, 2, 3, 4, 5])
    age = fr["age"].to_numpy()
    assert np.isnan(age[2]) and age[0] == 34
    inc = fr["income"].to_numpy()
    assert np.isnan(inc[1]) and inc[0] == 55000.5
    city = fr["city"]
    assert city.domain == ["austin", "boston", "chicago"]
    assert city.to_numpy()[4] == NA_ENUM  # "?" is an NA token
    assert fr["signup"].kind == "time"
    ms = fr["signup"].to_numpy()
    assert ms[0] < ms[1] < ms[3]  # chronological ordering preserved


def test_import_gz_and_glob(tmp_path, mesh8):
    (tmp_path / "part1.csv").write_text("a,b\n1,x\n2,y\n")
    with gzip.open(tmp_path / "part2.csv.gz", "wt") as f:
        f.write("a,b\n3,z\n")
    fr = import_file(str(tmp_path / "part*"))
    assert fr.nrows == 3
    np.testing.assert_allclose(sorted(fr["a"].to_numpy()), [1, 2, 3])


def test_headerless_and_tab(tmp_path, mesh8):
    p = tmp_path / "t.tsv"
    p.write_text("1\t2.5\tq\n3\t4.5\tr\n")
    fr = import_file(str(p))
    assert fr.names == ["C1", "C2", "C3"]
    np.testing.assert_allclose(fr["C2"].to_numpy(), [2.5, 4.5])
    assert fr["C3"].is_enum()


def test_quoted_fields(tmp_path, mesh8):
    p = tmp_path / "q.csv"
    p.write_text('name,v\n"a,b",1\n"say ""hi""",2\n')
    fr = import_file(str(p))
    assert fr["name"].domain == ["a,b", 'say "hi"']


def test_multiline_quoted_cell(tmp_path, mesh8):
    p = tmp_path / "m.csv"
    p.write_text('name,v\n"a\nb",1\n"c",2\n')
    fr = import_file(str(p))
    assert fr.nrows == 2
    assert fr["name"].domain == ["a\nb", "c"]
    np.testing.assert_allclose(fr["v"].to_numpy(), [1, 2])


def test_all_string_header_detected(tmp_path, mesh8):
    p = tmp_path / "s.csv"
    p.write_text("name,city\nalice,austin\nbob,boston\n")
    fr = import_file(str(p))
    assert fr.names == ["name", "city"]
    assert fr["city"].domain == ["austin", "boston"]


def test_ragged_row_fails_loudly(tmp_path, mesh8):
    p = tmp_path / "r.csv"
    p.write_text("a,b\n1,2\n3,4,5\n")
    with pytest.raises(ValueError, match="columns"):
        import_file(str(p))


def test_empty_split_part_rollups(mesh8):
    fr = Frame.from_arrays({"x": np.array([1.0, 2.0, 3.0], np.float32)})
    empty = fr.select_rows(np.zeros(3, dtype=bool))
    assert empty.nrows == 0
    r = empty["x"].rollups()
    assert np.isnan(r["mean"]) and r["nacnt"] == 0


def test_asnumeric_empty_domain(mesh8):
    v = Frame.from_arrays({"c": np.array([NA_ENUM, NA_ENUM], np.int32)},
                          domains={"c": []})["c"]
    out = v.asnumeric().to_numpy()
    assert np.isnan(out).all()


def test_col_types_override(csvfile):
    fr = import_file(csvfile, col_types={"id": "enum"})
    assert fr["id"].is_enum()


def test_select_rows_and_split(mesh8):
    rng = np.random.default_rng(3)
    fr = Frame.from_arrays({
        "x": rng.normal(size=500).astype(np.float32),
        "c": np.array(["u", "v"])[rng.integers(0, 2, size=500)],
    })
    sub = fr.select_rows(np.arange(0, 500, 5))
    assert sub.nrows == 100
    np.testing.assert_allclose(sub["x"].to_numpy(),
                               fr["x"].to_numpy()[::5])
    assert sub["c"].domain == fr["c"].domain

    parts = fr.split_frame([0.6, 0.2], seed=42)
    assert len(parts) == 3
    assert sum(p.nrows for p in parts) == 500
    assert abs(parts[0].nrows - 300) < 60


def test_rbind_cbind_asfactor(mesh8):
    a = Frame.from_arrays({"x": np.array([1.0, 2.0], np.float32),
                           "c": np.array(["p", "q"])})
    b = Frame.from_arrays({"x": np.array([3.0], np.float32),
                           "c": np.array(["r"])})
    r = a.rbind(b)
    assert r.nrows == 3
    assert r["c"].domain == ["p", "q", "r"]
    assert [r["c"].domain[i] for i in r["c"].to_numpy()] == ["p", "q", "r"]

    c = a.cbind(Frame.from_arrays({"x": np.array([9.0, 8.0], np.float32)}))
    assert c.names == ["x", "c", "x0"]

    v = Frame.from_arrays({"k": np.array([2.0, 1.0, 2.0, np.nan],
                                         np.float32)})["k"].asfactor()
    assert v.domain == ["1", "2"]
    assert v.to_numpy().tolist() == [1, 0, 1, NA_ENUM]
    back = v.asnumeric()
    out = back.to_numpy()
    assert out[0] == 2.0 and np.isnan(out[3])


def test_duplicate_headers_uniquified(tmp_path, mesh8):
    p = tmp_path / "dup.csv"
    p.write_text("a,a,b\n1,2,x\n3,4,y\n")
    fr = import_file(str(p))
    assert fr.names == ["a", "a2", "b"]
    assert fr["a"].to_numpy().tolist() == [1.0, 3.0]
    assert fr["a2"].to_numpy().tolist() == [2.0, 4.0]


def test_multifile_headerless_continuation(tmp_path, mesh8):
    (tmp_path / "p1.csv").write_text("a,b\n1,2\n")
    (tmp_path / "p2.csv").write_text("3,4\n5,6\n")
    fr = import_file(str(tmp_path))
    assert fr.nrows == 3
    assert sorted(fr["a"].to_numpy().tolist()) == [1.0, 3.0, 5.0]


def test_multifile_repeated_headers_dropped(tmp_path, mesh8):
    (tmp_path / "p1.csv").write_text("a,b\n1,2\n")
    (tmp_path / "p2.csv").write_text("a,b\n3,4\n")
    fr = import_file(str(tmp_path))
    assert fr.nrows == 2
    assert sorted(fr["a"].to_numpy().tolist()) == [1.0, 3.0]


def test_multifile_duplicate_repeated_headers_dropped(tmp_path, mesh8):
    # regression: uniquification must not mutate setup["names"], or the
    # second file's repeated header no longer matches and is kept as data
    (tmp_path / "p1.csv").write_text("a,a,b\n1,2,x\n")
    (tmp_path / "p2.csv").write_text("a,a,b\n3,4,y\n")
    fr = import_file(str(tmp_path))
    assert fr.nrows == 2
    assert fr.names == ["a", "a2", "b"]
    assert sorted(fr["a"].to_numpy().tolist()) == [1.0, 3.0]
    assert sorted(fr["b"].domain) == ["x", "y"]


# -- parquet / ORC ingest (VERDICT #9, reference h2o-parsers [U3]) -----------

def test_parquet_roundtrip(tmp_path, mesh8):
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 200
    rng = np.random.default_rng(3)
    xs = rng.normal(size=n).astype(np.float32)
    cats = np.array(["lo", "hi", "mid"])[rng.integers(0, 3, n)]
    ints = rng.integers(0, 100, n)
    table = pa.table({
        "x": pa.array(xs, type=pa.float32()),
        "g": pa.array(cats.tolist()),
        "k": pa.array(ints, type=pa.int64()),
        "d": pa.array(cats.tolist()).dictionary_encode(),
        "ts": pa.array(
            np.arange(n) * 86_400_000 + 1_600_000_000_000,
            type=pa.timestamp("ms")),
    })
    path = tmp_path / "t.parquet"
    pq.write_table(table, path)
    fr = import_file(str(path))
    assert fr.shape == (n, 5)
    np.testing.assert_allclose(fr["x"].to_numpy(), xs, rtol=1e-6)
    np.testing.assert_array_equal(fr["k"].to_numpy(), ints)
    assert fr["g"].is_enum() and sorted(fr["g"].domain) == ["hi", "lo", "mid"]
    assert fr["d"].is_enum()
    got_g = [fr["g"].domain[c] for c in fr["g"].to_numpy()]
    got_d = [fr["d"].domain[c] for c in fr["d"].to_numpy()]
    assert got_g == cats.tolist() == got_d
    assert fr["ts"].kind == "time"
    np.testing.assert_allclose(
        fr["ts"].to_numpy(),
        np.arange(n) * 86_400_000 + 1_600_000_000_000)


def test_parquet_nulls_and_multifile(tmp_path, mesh8):
    import pyarrow as pa
    import pyarrow.parquet as pq

    t1 = pa.table({"x": pa.array([1.0, None, 3.0]),
                   "s": pa.array(["a", None, "b"])})
    t2 = pa.table({"x": pa.array([4.0]), "s": pa.array(["a"])})
    pq.write_table(t1, tmp_path / "p1.parquet")
    pq.write_table(t2, tmp_path / "p2.parquet")
    fr = import_file(str(tmp_path / "p*.parquet"))
    assert fr.nrows == 4
    x = fr["x"].to_numpy()
    assert np.isnan(x[1]) and x[3] == 4.0
    s = fr["s"].to_numpy()
    assert s[1] == -1                        # NA enum code
    assert fr["s"].domain == ["a", "b"]


def test_parquet_col_type_override(tmp_path, mesh8):
    import pyarrow as pa
    import pyarrow.parquet as pq

    pq.write_table(pa.table({"k": pa.array([1, 2, 1, 2])}),
                   tmp_path / "o.parquet")
    fr = import_file(str(tmp_path / "o.parquet"),
                     col_types={"k": "enum"})
    assert fr["k"].is_enum()
    assert fr["k"].domain == ["1", "2"]


def test_orc_ingest(tmp_path, mesh8):
    import pyarrow as pa

    try:
        from pyarrow import orc
    except ImportError:
        import pytest
        pytest.skip("pyarrow.orc unavailable")
    table = pa.table({"a": pa.array([1.5, 2.5, 3.5]),
                      "b": pa.array(["x", "y", "x"])})
    path = tmp_path / "t.orc"
    orc.write_table(table, str(path))
    fr = import_file(str(path))
    np.testing.assert_allclose(fr["a"].to_numpy(), [1.5, 2.5, 3.5])
    assert fr["b"].is_enum()


# -- ARFF --------------------------------------------------------------------

ARFF_DOC = """% weather data
@relation weather
@attribute temp numeric
@attribute 'wind speed' real
@attribute outlook {sunny, rainy, 'very cloudy'}
@attribute note string
@data
71.0, 3.5, sunny, ok
?, 2.0, rainy, bad
65.5, ?, 'very cloudy', ok
"""


def test_arff_basic(tmp_path):
    import h2o_kubernetes_tpu as h2o

    p = tmp_path / "w.arff"
    p.write_text(ARFF_DOC)
    fr = import_file(str(p))
    assert fr.names == ["temp", "wind speed", "outlook", "note"]
    t = fr.vec("temp").to_numpy()
    assert np.isnan(t[1]) and abs(t[0] - 71.0) < 1e-5
    o = fr.vec("outlook")
    # DECLARED level order is kept (CSV inference would sort)
    assert o.domain == ["sunny", "rainy", "very cloudy"]
    assert list(o.to_numpy()) == [0, 1, 2]
    assert fr.vec("note").is_enum()
    w = fr.vec("wind speed").to_numpy()
    assert np.isnan(w[2])


def test_arff_content_sniff_without_extension(tmp_path):
    import h2o_kubernetes_tpu as h2o

    p = tmp_path / "noext.dat"
    p.write_text(ARFF_DOC)
    fr = import_file(str(p))
    assert fr.shape == (3, 4)


def test_arff_multifile_and_errors(tmp_path):
    import h2o_kubernetes_tpu as h2o
    import pytest

    (tmp_path / "a.arff").write_text(ARFF_DOC)
    (tmp_path / "b.arff").write_text(ARFF_DOC)
    fr = h2o.import_file(str(tmp_path / "*.arff"))
    assert fr.nrows == 6
    # sparse rows are rejected loudly
    bad = tmp_path / "sparse.arff"
    bad.write_text("@relation r\n@attribute a numeric\n@data\n{0 1}\n")
    with pytest.raises(ValueError, match="sparse"):
        h2o.import_file(str(bad))
    # out-of-domain nominal is a loud error
    bad2 = tmp_path / "dom.arff"
    bad2.write_text(
        "@relation r\n@attribute c {x, y}\n@data\nz\n")
    with pytest.raises(ValueError, match="declared domain"):
        h2o.import_file(str(bad2))


def test_arff_multifile_type_mismatch_rejected(tmp_path):
    import h2o_kubernetes_tpu as h2o
    import pytest

    (tmp_path / "a.arff").write_text(
        "@relation r\n@attribute c numeric\n@data\n1\n")
    (tmp_path / "b.arff").write_text(
        "@relation r\n@attribute c {x, y}\n@data\nx\n")
    with pytest.raises(ValueError, match="attributes differ"):
        h2o.import_file([str(tmp_path / "a.arff"),
                         str(tmp_path / "b.arff")])


def test_arff_unterminated_quote_diagnostic(tmp_path):
    import h2o_kubernetes_tpu as h2o
    import pytest

    p = tmp_path / "bad.arff"
    p.write_text("@relation r\n@attribute 'wind speed numeric\n@data\n")
    with pytest.raises(ValueError, match="unterminated"):
        h2o.import_file(str(p))


def test_arff_single_quoted_domains_and_values(tmp_path, mesh8):
    """ARFF conventionally single-quotes; a domain like {'a,b','c'} or a
    quoted data token with a comma must not mis-split (r2 ADVICE)."""
    p = tmp_path / "q.arff"
    p.write_text(
        "@relation t\n"
        "@attribute g {'a,b','c d',plain}\n"
        "@attribute x numeric\n"
        "@data\n"
        "'a,b',1\n"
        "'c d',2\n"
        "plain,3\n"
        "?,4\n")
    fr = import_file(str(p))
    v = fr.vec("g")
    assert v.domain == ["a,b", "c d", "plain"]
    codes = v.to_numpy().astype(int)
    assert list(codes[:3]) == [0, 1, 2] and codes[3] < 0
    np.testing.assert_allclose(fr.vec("x").to_numpy(), [1, 2, 3, 4])
