"""Persistence, MOJO export, and checkpoint-continuation tests
(reference: water/persist C20, h2o-genmodel/MOJO C18, SharedTree/DL
checkpoint §5.4 — SURVEY.md)."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import GBM, GLM, DeepLearning, KMeans

# long-running tier: deselect locally with -m 'not slow'
pytestmark = pytest.mark.slow


def _frame(n=400, seed=21):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    x0[::31] = np.nan
    g = np.array(["u", "v", "w"])[rng.integers(0, 3, n)]
    y = np.where(x1 + (g == "u") + rng.normal(scale=0.4, size=n) > 0,
                 "p", "n")
    return h2o.Frame.from_arrays({"x0": x0, "x1": x1, "g": g, "y": y})


class TestModelSaveLoad:
    def test_gbm_roundtrip(self, tmp_path, mesh8):
        fr = _frame()
        m = GBM(ntrees=5, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        path = h2o.save_model(m, str(tmp_path / "gbm.model"))
        m2 = h2o.load_model(path)
        np.testing.assert_allclose(m.predict_raw(fr), m2.predict_raw(fr),
                                   rtol=1e-6)
        assert m2.algo == "gbm" and m2.feature_names == m.feature_names

    def test_directory_naming_and_magic(self, tmp_path, mesh8):
        fr = _frame(200)
        m = GLM(family="binomial").train(y="y", training_frame=fr)
        path = h2o.save_model(m, str(tmp_path))
        assert path.endswith("glm.model")
        bad = tmp_path / "junk.model"
        bad.write_bytes(b"not a model")
        with pytest.raises(ValueError, match="not an h2o"):
            h2o.load_model(str(bad))


class TestFrameIO:
    def test_export_import_roundtrip(self, tmp_path, mesh8):
        fr = _frame(150)
        p = str(tmp_path / "out.csv")
        h2o.export_file(fr, p)
        fr2 = h2o.import_file(p)
        assert fr2.names == fr.names
        assert fr2.nrows == fr.nrows
        np.testing.assert_allclose(
            fr2["x1"].to_numpy(), fr["x1"].to_numpy(), rtol=1e-5)
        # NAs survive the trip
        assert np.isnan(fr2["x0"].to_numpy()[0:32:31]).all()
        assert list(fr2["g"].domain) == list(fr["g"].domain)

    def test_binary_frame_roundtrip(self, tmp_path, mesh8):
        fr = _frame(120)
        p = str(tmp_path / "fr.h2oframe")
        h2o.save_frame(fr, p)
        fr2 = h2o.load_frame(p)
        assert fr2.names == fr.names and fr2.nrows == fr.nrows
        np.testing.assert_array_equal(fr2["g"].to_numpy(),
                                      fr["g"].to_numpy())


class TestMojo:
    def test_gbm_mojo_matches(self, tmp_path, mesh8):
        fr = _frame()
        m = GBM(ntrees=6, max_depth=3, seed=2).train(
            y="y", training_frame=fr)
        p = str(tmp_path / "gbm.mojo")
        h2o.export_mojo(m, p)
        mj = h2o.import_mojo(p)
        cols = {n: fr[n].to_numpy() if not fr[n].is_enum() else
                np.array(fr[n].domain, dtype=object)[
                    np.maximum(fr[n].to_numpy(), 0)]
                for n in m.feature_names}
        # put NA back for enum codes < 0
        got = mj.predict(cols)
        want = m.predict_raw(fr)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_glm_mojo_matches(self, tmp_path, mesh8):
        fr = _frame(250)
        m = GLM(family="binomial").train(y="y", training_frame=fr)
        p = str(tmp_path / "glm.mojo")
        h2o.export_mojo(m, p)
        mj = h2o.import_mojo(p)
        X = np.stack([fr["x0"].to_numpy(), fr["x1"].to_numpy(),
                      fr["g"].to_numpy().astype(np.float32)], axis=1)
        got = mj.predict(X)
        want = m.predict_raw(fr)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_kmeans_mojo(self, tmp_path, mesh8):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(200, 2)).astype(np.float32)
        fr = h2o.Frame.from_arrays({"a": X[:, 0], "b": X[:, 1]})
        m = KMeans(k=3, seed=1).train(training_frame=fr)
        p = str(tmp_path / "km.mojo")
        h2o.export_mojo(m, p)
        mj = h2o.import_mojo(p)
        got = mj.predict({"a": X[:, 0], "b": X[:, 1]})
        want = m.predict(fr)["predict"].to_numpy()
        np.testing.assert_array_equal(got, want)


class TestCheckpoint:
    def test_gbm_continue(self, mesh8):
        fr = _frame()
        m5 = GBM(ntrees=5, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        m10 = GBM(ntrees=10, max_depth=3, seed=1, checkpoint=m5).train(
            y="y", training_frame=fr)
        assert m10.ntrees == 10
        # continued model fits training data at least as well
        a5 = m5.model_performance(fr, "y")["auc"]
        a10 = m10.model_performance(fr, "y")["auc"]
        assert a10 >= a5 - 1e-6

    def test_gbm_checkpoint_validation(self, mesh8):
        fr = _frame(200)
        m = GBM(ntrees=5, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        with pytest.raises(ValueError, match="must exceed"):
            GBM(ntrees=5, max_depth=3, checkpoint=m).train(
                y="y", training_frame=fr)
        with pytest.raises(ValueError, match="nbins/max_depth"):
            GBM(ntrees=9, max_depth=4, checkpoint=m).train(
                y="y", training_frame=fr)

    def test_dl_continue(self, mesh8):
        fr = _frame(300)
        m1 = DeepLearning(hidden=(16,), epochs=2, seed=1).train(
            y="y", training_frame=fr)
        m2 = DeepLearning(hidden=(16,), epochs=4, seed=1,
                          checkpoint=m1).train(y="y", training_frame=fr)
        a1 = m1.model_performance(fr, "y")["logloss"]
        a2 = m2.model_performance(fr, "y")["logloss"]
        assert a2 <= a1 * 1.1   # continued training didn't regress badly


def test_checkpoint_with_cv_rejected(mesh8):
    fr = _frame(200)
    m = GBM(ntrees=3, max_depth=3, seed=1).train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="cross-validation"):
        GBM(ntrees=6, max_depth=3, nfolds=3, checkpoint=m).train(
            y="y", training_frame=fr)


def test_export_quotes_roundtrip(tmp_path, mesh8):
    vals = np.array(['he said "hi"', "plain", "with,comma"], dtype=object)
    fr = h2o.Frame.from_arrays({"s": vals.astype(str),
                                "x": np.arange(3, dtype=np.float32)})
    p = str(tmp_path / "q.csv")
    h2o.export_file(fr, p)
    fr2 = h2o.import_file(p)
    assert sorted(fr2["s"].domain) == sorted(set(vals.astype(str)))
    assert fr2.nrows == 3


# -- remote persist schemes (VERDICT #9, water/persist registry [U3]) --------

class TestPersistSchemes:
    def test_mem_scheme_roundtrip(self, mesh8):
        fr = h2o.Frame.from_arrays({"x": np.arange(8.0),
                                "g": np.array(list("aabbccdd"))})
        h2o.save_frame(fr, "mem://bucket/f1")
        back = h2o.load_frame("mem://bucket/f1")
        np.testing.assert_array_equal(back["x"].to_numpy(),
                                      fr["x"].to_numpy())
        assert back["g"].domain == fr["g"].domain

    def test_mem_scheme_model(self, mesh8):
        fr = _frame()
        m = GBM(ntrees=3, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        h2o.save_model(m, "mem://models/gbm1.model")
        back = h2o.load_model("mem://models/gbm1.model")
        np.testing.assert_allclose(back.predict_raw(fr),
                                   m.predict_raw(fr), rtol=1e-6)

    def test_http_scheme_read(self, tmp_path, mesh8):
        import functools
        import http.server
        import threading

        fr = h2o.Frame.from_arrays({"x": np.arange(5.0)})
        h2o.save_frame(fr, str(tmp_path / "fr.bin"))
        handler = functools.partial(
            http.server.SimpleHTTPRequestHandler, directory=str(tmp_path))
        srv = http.server.ThreadingHTTPServer(("localhost", 0), handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            port = srv.server_address[1]
            back = h2o.load_frame(f"http://localhost:{port}/fr.bin")
            np.testing.assert_array_equal(back["x"].to_numpy(),
                                          np.arange(5.0))
        finally:
            srv.shutdown()

    def test_http_scheme_write_rejected(self, mesh8):
        fr = h2o.Frame.from_arrays({"x": np.arange(3.0)})
        with pytest.raises(ValueError):
            h2o.save_frame(fr, "http://example.invalid/f")

    def test_unknown_scheme_rejected(self, mesh8):
        fr = h2o.Frame.from_arrays({"x": np.arange(3.0)})
        with pytest.raises(ValueError):
            h2o.save_frame(fr, "s3q://nope/f")


# -- round-2 MOJO exports: DL / NB / PCA (VERDICT #10) -----------------------

class TestMojoRound2:
    def test_deeplearning_mojo_matches(self, tmp_path, mesh8):
        from h2o_kubernetes_tpu.models import DeepLearning

        fr = _frame()
        m = DeepLearning(hidden=[8, 8], epochs=3, seed=2).train(
            y="y", training_frame=fr)
        p = str(tmp_path / "dl.zip")
        h2o.export_mojo(m, p)
        mj = h2o.import_mojo(p)
        data = {n: fr[n].to_numpy() if not fr[n].is_enum() else
                np.array([fr[n].domain[c] if c >= 0 else None
                          for c in fr[n].to_numpy()], dtype=object)
                for n in m.feature_names}
        got = mj.predict(data)
        want = m.predict_raw(fr)
        np.testing.assert_allclose(got, np.asarray(want), rtol=2e-4,
                                   atol=2e-5)

    def test_naivebayes_mojo_matches(self, tmp_path, mesh8):
        from h2o_kubernetes_tpu.models import NaiveBayes

        fr = _frame()
        m = NaiveBayes().train(y="y", training_frame=fr)
        p = str(tmp_path / "nb.zip")
        h2o.export_mojo(m, p)
        mj = h2o.import_mojo(p)
        data = {n: fr[n].to_numpy() if not fr[n].is_enum() else
                np.array([fr[n].domain[c] if c >= 0 else None
                          for c in fr[n].to_numpy()], dtype=object)
                for n in m.feature_names}
        np.testing.assert_allclose(mj.predict(data),
                                   np.asarray(m.predict_raw(fr)),
                                   rtol=2e-4, atol=2e-5)

    def test_pca_mojo_matches(self, tmp_path, mesh8):
        from h2o_kubernetes_tpu.models import PCA

        fr = _frame()
        m = PCA(k=2).train(training_frame=fr.drop("y"))
        p = str(tmp_path / "pca.zip")
        h2o.export_mojo(m, p)
        mj = h2o.import_mojo(p)
        data = {n: fr[n].to_numpy() if not fr[n].is_enum() else
                np.array([fr[n].domain[c] if c >= 0 else None
                          for c in fr[n].to_numpy()], dtype=object)
                for n in m.feature_names}
        np.testing.assert_allclose(mj.predict(data),
                                   np.asarray(m.predict_raw(fr.drop("y"))),
                                   rtol=2e-4, atol=2e-4)

    def test_word2vec_mojo(self, tmp_path, mesh8):
        from h2o_kubernetes_tpu.models import Word2Vec

        rng = np.random.default_rng(3)
        words = ["king", "queen", "man", "woman", "apple", "pear"]
        toks = []
        for _ in range(150):
            toks += list(rng.choice(words[:4], 5)) + [None]
        for _ in range(150):
            toks += list(rng.choice(words[4:], 5)) + [None]
        fr = h2o.Frame.from_arrays(
            {"words": np.array(toks, dtype=object)})
        m = Word2Vec(vec_size=8, epochs=3, min_word_freq=2,
                     seed=1).train(fr)
        p = str(tmp_path / "w2v.zip")
        h2o.export_mojo(m, p)
        mj = h2o.import_mojo(p)
        np.testing.assert_allclose(mj.word_vector("king"),
                                   np.asarray(m.W)[m.word_index["king"]],
                                   rtol=1e-6)
        syn = mj.find_synonyms("king", count=3)
        assert len(syn) == 3 and "king" not in syn


def test_isolationforest_mojo_roundtrip(tmp_path):
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import IsolationForest

    rng = np.random.default_rng(3)
    n = 300
    X = rng.normal(size=(n, 4)).astype(np.float32)
    X[:5] += 6.0                              # planted anomalies
    fr = h2o.Frame.from_arrays(
        {f"x{i}": X[:, i] for i in range(4)})
    m = IsolationForest(ntrees=20, seed=1).train(training_frame=fr)
    in_proc = m.predict(fr)
    p = str(tmp_path / "iso.mojo")
    h2o.export_mojo(m, p)
    mm = h2o.import_mojo(p)
    out = mm.predict(fr)
    np.testing.assert_allclose(out[:, 0],
                               in_proc.vec("predict").to_numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out[:, 1],
                               in_proc.vec("mean_length").to_numpy(),
                               rtol=1e-4, atol=1e-4)
    # anomalies score higher than the bulk
    assert out[:5, 0].min() > np.median(out[5:, 0])


def test_mojo_predict_accepts_frame_directly(tmp_path, mesh8):
    """MojoModel.predict(frame) decodes enum codes through the SCORING
    frame's own domain (h2o genmodel takes raw values, not codes)."""
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    fr = _frame()
    m = GBM(ntrees=4, max_depth=3, seed=2).train(
        y="y", training_frame=fr)
    p = str(tmp_path / "gbm2.mojo")
    h2o.export_mojo(m, p)
    mj = h2o.import_mojo(p)
    got = mj.predict(fr)
    np.testing.assert_allclose(got, m.predict_raw(fr),
                               rtol=1e-4, atol=1e-5)


def test_mojo_frame_kind_mismatch_raises(tmp_path, mesh8):
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    fr = _frame()
    m = GBM(ntrees=3, max_depth=2, seed=2).train(
        y="y", training_frame=fr)
    p = str(tmp_path / "gbm3.mojo")
    h2o.export_mojo(m, p)
    mj = h2o.import_mojo(p)
    # swap the enum feature for a numeric column of the same name
    enum_cols = [n for n in m.feature_names
                 if m.feature_domains.get(n) is not None]
    assert enum_cols, "fixture needs an enum feature"
    bad = {n: fr[n] for n in fr.names}
    import numpy as np
    bad[enum_cols[0]] = h2o.Vec.from_numpy(
        np.zeros(fr.nrows, dtype=np.float32), enum_cols[0])
    bad_fr = h2o.Frame(bad)
    with pytest.raises(ValueError, match="categorical at training"):
        mj.predict(bad_fr)


def test_load_model_backfills_missing_cover(tmp_path, mesh8):
    """Binary models saved before Tree grew `cover` (6-field pickles)
    must still load: predict works, contributions ask for a retrain
    (r2 ADVICE)."""
    from h2o_kubernetes_tpu.models.tree.core import Tree

    fr = _frame()
    m = GBM(ntrees=4, max_depth=3, seed=7).train(y="y", training_frame=fr)
    want = np.asarray(m.predict_raw(fr))
    # simulate a pre-cover artifact: drop the cover field before saving
    m.trees = Tree(m.trees.split_feat, m.trees.split_bin, m.trees.na_left,
                   m.trees.is_split, m.trees.value, m.trees.gain)
    assert m.trees.cover is None
    path = h2o.save_model(m, str(tmp_path / "old.model"))
    m2 = h2o.load_model(path)
    assert np.isnan(np.asarray(m2.trees.cover)).all()
    np.testing.assert_allclose(np.asarray(m2.predict_raw(fr)), want,
                               rtol=1e-6)
    with pytest.raises(ValueError, match="per-node cover"):
        m2.predict_contributions(fr)


def test_load_model_refuses_foreign_classes(tmp_path, mesh8):
    """A tampered model file referencing classes outside the package
    (the classic pickle-RCE shape) must be refused, not executed."""
    import pickle

    from h2o_kubernetes_tpu.persist import _MAGIC

    class Evil:
        def __reduce__(self):
            import os

            return (os.system, ("echo pwned",))

    p = tmp_path / "evil.model"
    p.write_bytes(_MAGIC + pickle.dumps(Evil()))
    with pytest.raises(pickle.UnpicklingError, match="outside the"):
        h2o.load_model(str(p))
    # bypass shape 2: reach a module RE-EXPORTED by a package module
    # (persist.py imports os) via the package-prefix rule
    raw = (b"\x80\x04c" + b"h2o_kubernetes_tpu.persist\nos\n" + b".")
    p2 = tmp_path / "evil2.model"
    p2.write_bytes(_MAGIC + raw)
    with pytest.raises(pickle.UnpicklingError, match="outside the"):
        h2o.load_model(str(p2))
    # bypass shape 3: package-level FUNCTION with attacker args
    raw3 = (b"\x80\x04c" + b"h2o_kubernetes_tpu.persist\nwrite_bytes\n"
            + b".")
    p3 = tmp_path / "evil3.model"
    p3.write_bytes(_MAGIC + raw3)
    with pytest.raises(pickle.UnpicklingError, match="outside the"):
        h2o.load_model(str(p3))
