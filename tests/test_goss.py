"""GOSS gradient-based one-side sampling through the training stack
(ISSUE 13, arXiv:1809.04559; docs/SCALING.md "Gradient-based
sampling"):

- kill-switch bitwise parity: H2O_TPU_GOSS=0 (and unset) trace the
  exact pre-GOSS program — identical trees and predictions;
- the a+b=1 identity: with the whole row set kept at amplification 1
  the masking + compaction + full-row re-descent plumbing must be
  provably NEUTRAL — bitwise-equal to unsampled training end to end;
- seeded determinism: the per-row (round key, global row id) hash
  draws are reproducible run to run;
- amplified-weight gain unbiasedness on an exact-sum fixture: the
  trained root split/gain equals a host recomputation from explicitly
  factor-amplified histograms (dyadic gradients, dyadic (1-a)/b
  amplification — every sum exact, any deviation is a bug);
- EFB + GOSS composition: bundled vs unbundled training with sampling
  on stays bitwise on the zero-conflict exact fixture;
- ooc-chunk path equivalence vs in-HBM at the same seed: the
  layout-invariant selection rule picks the SAME rows, so the streamed
  model is bitwise-equal where sums are exact (single round) and
  float-close in general — the same contract test_chunked_path pins
  for unsampled ooc;
- the AUC-parity gate: |ΔAUC| <= 0.002 vs unsampled at matched tree
  count on the 100k airlines shape (a=0.1, b=0.1);
- DRF stays bagged/unsampled; knob validation; CV folds and the
  compile-ahead mirror ride along.
"""

import os

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import DRF, GBM, XGBoost
from tools import datasets as D

GOSS_KEYS = ("H2O_TPU_GOSS", "H2O_TPU_GOSS_TOP_A", "H2O_TPU_GOSS_RAND_B")


def _set_goss(monkeypatch, on: bool, a: str | None = None,
              b: str | None = None):
    for k in GOSS_KEYS:
        monkeypatch.delenv(k, raising=False)
    if on:
        monkeypatch.setenv("H2O_TPU_GOSS", "1")
        if a is not None:
            monkeypatch.setenv("H2O_TPU_GOSS_TOP_A", a)
        if b is not None:
            monkeypatch.setenv("H2O_TPU_GOSS_RAND_B", b)


def _tree_arrays(m):
    import jax

    return [np.asarray(a) for a in jax.tree.flatten(m.trees)[0]]


def _assert_trees_equal(m1, m2):
    for a, b in zip(_tree_arrays(m1), _tree_arrays(m2)):
        np.testing.assert_array_equal(a, b)


def _bern_frame(n=4096, seed=0, F=6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = np.where(X[:, 0] + 0.5 * X[:, 1] +
                 rng.normal(scale=0.5, size=n) > 0, "p", "n")
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["y"] = y
    return h2o.Frame.from_arrays(cols)


def _exact_gaussian_frame(n=4096, seed=11, F=5):
    """y ∈ {0,1} exactly even: init is exactly 0.5, round-1 gradients
    are ±0.5, and with a dyadic amplification every histogram partial
    sum is exactly representable — association order cannot change a
    bit (the test_chunked_path construction)."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = np.zeros(n, dtype=np.float32)
    y[rng.permutation(n)[: n // 2]] = 1.0
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["y"] = y
    return h2o.Frame.from_arrays(cols)


# amp = (1-a)/b = 2 — dyadic, so amplified sums stay exact
DYADIC_A, DYADIC_B = "0.5", "0.25"


def test_kill_switch_bitwise(mesh8, monkeypatch):
    """H2O_TPU_GOSS=0 and the unset default must produce identical
    trees (the off path traces byte-identically to a build without the
    feature), and a sampled config must actually differ."""
    fr = _bern_frame()
    _set_goss(monkeypatch, False)
    m_def = GBM(ntrees=4, max_depth=4, seed=3).train(
        y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_GOSS", "0")
    m_kill = GBM(ntrees=4, max_depth=4, seed=3).train(
        y="y", training_frame=fr)
    _assert_trees_equal(m_def, m_kill)
    np.testing.assert_array_equal(m_def.predict_raw(fr),
                                  m_kill.predict_raw(fr))
    _set_goss(monkeypatch, True, "0.2", "0.2")
    m_on = GBM(ntrees=4, max_depth=4, seed=3).train(
        y="y", training_frame=fr)
    assert not all(np.array_equal(a, b) for a, b in
                   zip(_tree_arrays(m_def), _tree_arrays(m_on)))


def test_identity_when_a_plus_b_covers_all_rows(mesh8, monkeypatch):
    """a=0.5, b=0.5: every row is kept at amplification (1-a)/b = 1,
    so GOSS-on must be BITWISE-equal to unsampled training — the
    structural proof that masking, static-cap compaction and the
    full-row re-descent margin update are neutral plumbing."""
    fr = _bern_frame(seed=5)
    _set_goss(monkeypatch, False)
    m_off = GBM(ntrees=5, max_depth=4, seed=2).train(
        y="y", training_frame=fr)
    _set_goss(monkeypatch, True, "0.5", "0.5")
    m_id = GBM(ntrees=5, max_depth=4, seed=2).train(
        y="y", training_frame=fr)
    _assert_trees_equal(m_off, m_id)
    np.testing.assert_array_equal(m_off.predict_raw(fr),
                                  m_id.predict_raw(fr))


def test_seeded_determinism(mesh8, monkeypatch):
    """Two runs at one seed draw identical keep patterns (the hashed
    (round key, global row id) stream); a different seed differs."""
    fr = _bern_frame(seed=1)
    _set_goss(monkeypatch, True, "0.2", "0.3")
    kw = dict(ntrees=4, max_depth=4)
    m1 = GBM(seed=9, **kw).train(y="y", training_frame=fr)
    m2 = GBM(seed=9, **kw).train(y="y", training_frame=fr)
    _assert_trees_equal(m1, m2)
    np.testing.assert_array_equal(m1.predict_raw(fr),
                                  m2.predict_raw(fr))
    m3 = GBM(seed=10, **kw).train(y="y", training_frame=fr)
    assert not all(np.array_equal(a, b) for a, b in
                   zip(_tree_arrays(m1), _tree_arrays(m3)))


def test_amplified_gain_unbiasedness_exact(mesh8, monkeypatch):
    """The unbiasedness contract, pinned exactly: recompute the GOSS
    factors host-side through the SAME shared helpers (goss_round_keys
    → threshold → per-row factor on global row ids), build the
    explicitly (1-a)/b-amplified root histogram with numpy adds, and
    the trained tree's root (feature, bin, gain, cover) must match a
    fresh _find_splits over it to the last bit — dyadic gradients
    (±0.5) and dyadic amplification (×2) make every sum exact."""
    import jax
    import jax.numpy as jnp

    from h2o_kubernetes_tpu.models.gbm import _make_tree_params
    from h2o_kubernetes_tpu.models.tree import core as C
    from h2o_kubernetes_tpu.models.tree.binning import apply_bins_jit

    fr = _exact_gaussian_frame()
    n = fr.nrows
    _set_goss(monkeypatch, True, DYADIC_A, DYADIC_B)
    m = GBM(ntrees=1, max_depth=2, distribution="gaussian", seed=6,
            min_rows=4.0).train(y="y", training_frame=fr)
    a, b = float(DYADIC_A), float(DYADIC_B)

    X = m._design_matrix(fr)
    binned = np.asarray(apply_bins_jit(
        X, m._edges, m._enum_mask, m.bin_spec.na_bin))
    padded = binned.shape[0]
    w = np.zeros(padded, dtype=np.float32)
    w[:n] = 1.0
    y = np.zeros(padded, dtype=np.float32)
    y[:n] = fr.vec("y").to_numpy()[:n]
    assert float(m.init_score) == 0.5          # exact even split
    g = np.float32(0.5) - y                    # margin0 - y, ±0.5

    # the reference factor stream — same helpers, global row ids
    kg = C.goss_round_keys(jax.random.key(6), 1)[0]
    absg = C.goss_rank_stat(jnp.asarray(g), jnp.asarray(w))
    live = jnp.asarray(w) > 0
    mmax = jnp.max(absg)
    counts, total = C.goss_local_counts(absg, live, mmax)
    T, frac = C.goss_threshold(counts, total, a)
    factor = np.asarray(C.goss_row_factor(
        absg, live, mmax, T, frac, kg,
        jnp.arange(padded, dtype=jnp.int32), a, b))
    assert set(np.unique(factor)).issubset({0.0, 1.0, 2.0})
    kept = float((factor > 0)[w > 0].mean())
    assert abs(kept - (a + b)) < 0.05          # expected a+b fraction

    # explicitly amplified root histogram (numpy, exact dyadic sums)
    w_amp = w * factor
    F, B = binned.shape[1], m.params.nbins
    hist = np.zeros((1, F, B, 3), dtype=np.float32)
    for f in range(F):
        np.add.at(hist[0, f], binned[:, f],
                  np.stack([g * w_amp, w_amp, w_amp], axis=1))
    tp = _make_tree_params(m.params, "gaussian")
    feat, bin_, _, can, _, gain, cover, _, _ = C._find_splits(
        jnp.asarray(hist), tp)
    assert bool(can[0])
    assert int(m.trees.split_feat[0, 0]) == int(feat[0])
    assert int(m.trees.split_bin[0, 0]) == int(bin_[0])
    assert float(m.trees.gain[0, 0]) == float(gain[0])
    assert float(m.trees.cover[0, 0]) == float(cover[0])


def test_efb_goss_composition(mesh8, monkeypatch):
    """Bundled vs unbundled training with GOSS ON: the sampling factor
    depends only on gradients (identical both ways), so the EFB
    exactness contract carries through — identical splits, bitwise
    predictions on the zero-conflict exact fixture."""
    rng = np.random.default_rng(4)
    ne = 4096
    ecols = {}
    cat_e = rng.integers(0, 16, size=(4, ne))
    for gi in range(4):
        for k in range(16):
            ecols[f"c{gi}_{k}"] = (cat_e[gi] == k).astype(np.float32)
    ecols["dx"] = rng.normal(size=ne).astype(np.float32)
    ecols["ye"] = ((cat_e[0] == 1).astype(np.float32) - (cat_e[1] == 2)
                   + (ecols["dx"] > 0)).astype(np.float32)
    fr_e = h2o.Frame.from_arrays(ecols)
    _set_goss(monkeypatch, True, DYADIC_A, DYADIC_B)

    def _leg(env):
        monkeypatch.setenv("H2O_TPU_EFB", env)
        try:
            return GBM(ntrees=1, max_depth=4, seed=0).train(
                y="ye", training_frame=fr_e)
        finally:
            monkeypatch.delenv("H2O_TPU_EFB", raising=False)

    m_b = _leg("1")
    m_u = _leg("0")
    isp = np.asarray(m_u.trees.is_split)
    np.testing.assert_array_equal(isp, np.asarray(m_b.trees.is_split))
    for fld in ("split_feat", "split_bin", "na_left"):
        np.testing.assert_array_equal(
            np.where(isp, np.asarray(getattr(m_u.trees, fld)), -9),
            np.where(isp, np.asarray(getattr(m_b.trees, fld)), -9),
            err_msg=fld)
    np.testing.assert_array_equal(np.asarray(m_u.predict_raw(fr_e)),
                                  np.asarray(m_b.predict_raw(fr_e)))


def test_ooc_matches_in_hbm_same_seed(mesh8, monkeypatch):
    """The streamed chunk grid selects the SAME rows as the fused
    in-HBM layout at one seed (layout-invariant threshold + per-row
    hash): bitwise-equal trees/predictions on the single exact-sum
    round, float-close over multiple rounds (the chunk-boundary
    reassociation caveat, same as unsampled ooc)."""
    fr = _exact_gaussian_frame(seed=13)
    _set_goss(monkeypatch, True, DYADIC_A, DYADIC_B)
    kw = dict(ntrees=1, max_depth=3, distribution="gaussian", seed=3,
              min_rows=4.0)
    monkeypatch.setenv("H2O_TPU_OOC", "0")
    m_hbm = GBM(**kw).train(y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC", "1")
    monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "1024")
    m_ooc = GBM(**kw).train(y="y", training_frame=fr)
    _assert_trees_equal(m_hbm, m_ooc)
    np.testing.assert_array_equal(m_hbm.predict_raw(fr),
                                  m_ooc.predict_raw(fr))
    # multi-round: general f32 gradients → tolerance, like unsampled
    kw2 = dict(ntrees=4, max_depth=3, distribution="gaussian", seed=3)
    monkeypatch.setenv("H2O_TPU_OOC", "0")
    m_h2 = GBM(**kw2).train(y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_OOC", "1")
    m_o2 = GBM(**kw2).train(y="y", training_frame=fr)
    p1, p2 = m_h2.predict_raw(fr), m_o2.predict_raw(fr)
    assert np.allclose(p1, p2, atol=2e-3), np.abs(p1 - p2).max()
    # streamed vs resident chunks stay bitwise with GOSS on
    monkeypatch.setenv("H2O_TPU_OOC_RESIDENT", "1")
    m_res = GBM(**kw2).train(y="y", training_frame=fr)
    monkeypatch.delenv("H2O_TPU_OOC_RESIDENT", raising=False)
    _assert_trees_equal(m_o2, m_res)


def test_auc_parity_100k_airlines(mesh8, monkeypatch):
    """The acceptance gate: |ΔAUC| <= 0.002 vs unsampled at matched
    tree count on the 100k airlines shape with the default a=0.1,
    b=0.1 — the sampled model must not trade measurable accuracy for
    its 3-5× histogram-row reduction."""
    fr = D.airlines_frame(100_000, seed=7)

    def _leg(on: bool):
        _set_goss(monkeypatch, on, "0.1", "0.1")
        return GBM(ntrees=10, max_depth=5, nbins=64, learn_rate=0.2,
                   seed=1).train(y="IsDepDelayed", training_frame=fr)

    auc_off = _leg(False).scoring_history[-1]["train_auc"]
    auc_on = _leg(True).scoring_history[-1]["train_auc"]
    assert auc_off > 0.7                     # the model actually fits
    assert abs(auc_off - auc_on) <= 0.002, (auc_off, auc_on)


def test_drf_stays_bagged(mesh8, monkeypatch):
    """DRF ignores the GOSS knobs entirely (trees vote from bootstrap
    bags — there is no gradient to rank by)."""
    fr = _bern_frame(n=2048, seed=8)
    _set_goss(monkeypatch, True, "0.1", "0.1")
    m_on = DRF(ntrees=4, max_depth=3, seed=2).train(
        y="y", training_frame=fr)
    _set_goss(monkeypatch, False)
    m_off = DRF(ntrees=4, max_depth=3, seed=2).train(
        y="y", training_frame=fr)
    _assert_trees_equal(m_on, m_off)


def test_multinomial_and_xgboost_goss(mesh8, monkeypatch):
    """K-class rounds share ONE GOSS draw (rows ranked by the class-L1
    gradient norm) and stay deterministic; XGBoost-hist rides the same
    stack and its sampled model differs from unsampled."""
    rng = np.random.default_rng(2)
    n = 2048
    x = rng.normal(size=n).astype(np.float32)
    y3 = np.where(x > 0.5, "a", np.where(x < -0.5, "b", "c"))
    fr3 = h2o.Frame.from_arrays(
        {"x": x, "x2": rng.normal(size=n).astype(np.float32), "y": y3})
    _set_goss(monkeypatch, True, "0.2", "0.3")
    m1 = GBM(ntrees=3, max_depth=3, seed=0).train(
        y="y", training_frame=fr3)
    m2 = GBM(ntrees=3, max_depth=3, seed=0).train(
        y="y", training_frame=fr3)
    _assert_trees_equal(m1, m2)
    assert m1.ntrees == 9                   # 3 rounds x 3 class trees
    fr = _bern_frame(n=2048, seed=3)
    mx_on = XGBoost(ntrees=3, max_depth=4, seed=1).train(
        y="y", training_frame=fr)
    _set_goss(monkeypatch, False)
    mx_off = XGBoost(ntrees=3, max_depth=4, seed=1).train(
        y="y", training_frame=fr)
    assert not all(np.array_equal(a, b) for a, b in
                   zip(_tree_arrays(mx_on), _tree_arrays(mx_off)))


def test_knob_validation(mesh8, monkeypatch):
    """Bad knobs and the sample_rate conflict fail loudly up front."""
    fr = _bern_frame(n=512, seed=4)
    _set_goss(monkeypatch, True, "0.9", "0.5")    # a + b > 1
    with pytest.raises(ValueError, match="GOSS"):
        GBM(ntrees=1, max_depth=2, seed=0).train(
            y="y", training_frame=fr)
    _set_goss(monkeypatch, True, "0.1", "0")      # b must be > 0
    with pytest.raises(ValueError, match="GOSS"):
        GBM(ntrees=1, max_depth=2, seed=0).train(
            y="y", training_frame=fr)
    _set_goss(monkeypatch, True)
    with pytest.raises(ValueError, match="sample_rate"):
        GBM(ntrees=1, max_depth=2, seed=0, sample_rate=0.8).train(
            y="y", training_frame=fr)


def test_compaction_overflow_warns(mesh8, monkeypatch, caplog):
    """A frame whose row order clusters the high-gradient rows into
    one shard overflows the static compaction capacity — the dropped
    contributions must surface as a LOUD warning (never silent), and
    training must still complete. A shuffled layout with the same
    knobs must not warn."""
    import logging

    n = 4096
    rng = np.random.default_rng(3)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.zeros(n, dtype=np.float32)
    y[3584:] = 10.0        # all the |g| mass in the LAST shard's rows
    cols = {f"f{i}": X[:, i] for i in range(4)}
    cols["y"] = y
    fr = h2o.Frame.from_arrays(cols)
    _set_goss(monkeypatch, True, "0.1", "0.05")
    with caplog.at_level(logging.WARNING, logger="h2o_kubernetes_tpu"):
        m = GBM(ntrees=1, max_depth=3, distribution="gaussian",
                seed=1).train(y="y", training_frame=fr)
    assert m.ntrees == 1
    assert any("GOSS compaction overflow" in r.message
               for r in caplog.records)
    caplog.clear()
    perm = rng.permutation(n)
    cols2 = {f"f{i}": X[perm, i] for i in range(4)}
    cols2["y"] = y[perm]
    fr2 = h2o.Frame.from_arrays(cols2)
    with caplog.at_level(logging.WARNING, logger="h2o_kubernetes_tpu"):
        GBM(ntrees=1, max_depth=3, distribution="gaussian",
            seed=1).train(y="y", training_frame=fr2)
    assert not any("GOSS compaction overflow" in r.message
                   for r in caplog.records)


def test_cv_and_compile_ahead_ride_along(mesh8, monkeypatch):
    """CV folds inherit the knob (each fold trains sampled) and the
    compile-ahead mirror pre-lowers the GOSS dispatch shape — the
    (round keys, goss keys) operand pair — without error."""
    fr = _bern_frame(n=2048, seed=6)
    _set_goss(monkeypatch, True, "0.2", "0.2")
    m = GBM(ntrees=3, max_depth=3, seed=1, nfolds=2,
            fold_assignment="modulo").train(y="y", training_frame=fr)
    assert np.isfinite(m.cross_validation_metrics()["auc"])
    est = GBM(ntrees=3, max_depth=3, seed=1)
    thunks = est.compile_ahead_lowerings("y", fr)
    assert thunks
    thunks[0]()        # the mirrored AOT shape must lower + compile
    # GOSS + sample_rate conflict returns no thunks (train() raises)
    est2 = GBM(ntrees=3, max_depth=3, seed=1, sample_rate=0.5)
    assert est2.compile_ahead_lowerings("y", fr) == []
