"""Threshold-table binomial metrics (ModelMetricsBinomial analogs) —
parity-checked against sklearn on the same predictions."""

import numpy as np
import pytest

from h2o_kubernetes_tpu import metrics as M


@pytest.fixture(scope="module")
def scored():
    rng = np.random.default_rng(3)
    n = 5000
    y = (rng.random(n) < 0.35).astype(np.float32)
    p = np.clip(y * 0.4 + rng.normal(scale=0.25, size=n) + 0.3, 0, 1)
    return y, p.astype(np.float32)


def test_stats_match_sklearn(scored):
    y, p = scored
    from sklearn import metrics as SK

    stats = M.binomial_stats(y, p)
    assert abs(stats["auc"] - SK.roc_auc_score(y, p)) < 2e-3
    assert abs(stats["gini"] - (2 * SK.roc_auc_score(y, p) - 1)) < 4e-3
    prec, rec, _ = SK.precision_recall_curve(y, p)
    assert abs(stats["pr_auc"] - SK.auc(rec, prec)) < 2e-2
    # max F1 over sklearn's threshold sweep
    f1s = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
    assert abs(stats["f1"] - f1s.max()) < 5e-3
    t = stats["max_f1_threshold"]
    pred = p >= t
    sk_f1 = SK.f1_score(y, pred)
    assert abs(stats["f1"] - sk_f1) < 5e-3


def test_confusion_matrix_explicit_threshold(scored):
    y, p = scored
    cm = M.confusion_matrix(y, p, threshold=0.5)
    pred = p >= 0.5
    want = np.array([[np.sum(~pred & (y == 0)), np.sum(pred & (y == 0))],
                     [np.sum(~pred & (y == 1)), np.sum(pred & (y == 1))]])
    np.testing.assert_allclose(cm, want)


def test_confusion_matrix_f1_default_consistent(scored):
    y, p = scored
    stats = M.binomial_stats(y, p)
    cm = M.confusion_matrix(y, p)          # F1-optimal threshold
    tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
    f1 = 2 * tp / max(2 * tp + fp + fn, 1e-12)
    assert abs(f1 - stats["f1"]) < 5e-3


def test_single_class_raises():
    y = np.ones(100, dtype=np.float32)
    p = np.linspace(0, 1, 100).astype(np.float32)
    with pytest.raises(ValueError, match="both classes"):
        M.binomial_stats(y, p)


def test_model_performance_includes_threshold_metrics():
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    rng = np.random.default_rng(1)
    n = 400
    x = rng.normal(size=n).astype(np.float32)
    fr = h2o.Frame.from_arrays({
        "x": x, "y": np.where(x + rng.normal(scale=0.4, size=n) > 0,
                              "b", "a")})
    m = GBM(ntrees=5, max_depth=3, seed=0).train(
        y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    for k in ("pr_auc", "gini", "f1", "mean_per_class_error"):
        assert k in perf, k
    cm = m.confusion_matrix(fr, "y")
    assert cm.shape == (2, 2)
    assert cm.sum() == n


def test_nan_scores_surface_as_nan_stats(scored):
    y, p = scored
    p2 = p.copy(); p2[5] = np.nan
    stats = M.binomial_stats(y, p2)
    assert np.isnan(stats["auc"]) and np.isnan(stats["pr_auc"])
    assert np.isnan(stats["confusion"]).all()


def test_multinomial_perf_includes_macro_auc_and_mpce():
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM
    from sklearn import metrics as SK

    rng = np.random.default_rng(5)
    n = 450
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    cls = np.where(x0 > 0.5, "a", np.where(x1 > 0, "b", "c"))
    fr = h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": cls})
    m = GBM(ntrees=5, max_depth=3, seed=0).train(
        y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert {"logloss", "accuracy", "mean_per_class_error",
            "auc"} <= set(perf)
    # macro-OVR AUC parity with sklearn on the same predictions
    preds = m.predict_raw(fr)
    dom = m.response_domain
    yc = fr.vec("y").to_numpy()
    want = SK.roc_auc_score(yc, preds, multi_class="ovr",
                            average="macro", labels=range(len(dom)))
    assert abs(perf["auc"] - want) < 2e-3
    assert 0 <= perf["mean_per_class_error"] <= 1
