import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu.frame import NA_ENUM


def _frame(mesh8):
    rng = np.random.default_rng(1)
    x = rng.normal(2.0, 3.0, size=1000).astype(np.float32)
    x[::17] = np.nan
    cat = np.array(["a", "b", "c"])[rng.integers(0, 3, size=1000)]
    y = rng.integers(0, 2, size=1000).astype(np.float32)
    return Frame.from_arrays({"x": x, "cat": cat, "y": y}), x, cat


def test_shapes_and_names(mesh8):
    fr, x, cat = _frame(mesh8)
    assert fr.shape == (1000, 3)
    assert fr.names == ["x", "cat", "y"]
    assert fr["cat"].is_enum()
    assert fr["cat"].domain == ["a", "b", "c"]


def test_rollups_match_numpy(mesh8):
    fr, x, cat = _frame(mesh8)
    r = fr["x"].rollups()
    valid = x[~np.isnan(x)]
    np.testing.assert_allclose(r["mean"], valid.mean(), rtol=1e-4)
    np.testing.assert_allclose(r["sigma"], valid.std(ddof=1), rtol=1e-3)
    np.testing.assert_allclose(r["min"], valid.min(), rtol=1e-6)
    np.testing.assert_allclose(r["max"], valid.max(), rtol=1e-6)
    assert r["nacnt"] == int(np.isnan(x).sum())


def test_enum_roundtrip_and_na(mesh8):
    codes = np.array([0, 1, NA_ENUM, 2, 1], dtype=np.int32)
    fr = Frame.from_arrays({"c": codes}, domains={"c": ["x", "y", "z"]})
    v = fr["c"]
    assert v.nacnt() == 1
    assert v.cardinality() == 3
    back = v.to_numpy()
    np.testing.assert_array_equal(back, codes)


def test_to_matrix_and_mask(mesh8):
    fr, x, cat = _frame(mesh8)
    m = fr.to_matrix(["x", "y"])
    assert m.shape[1] == 2
    mask = fr.valid_mask()
    assert float(mask.sum()) == 1000


def test_subframe_drop(mesh8):
    fr, *_ = _frame(mesh8)
    assert fr[["x", "y"]].names == ["x", "y"]
    assert fr.drop("cat").names == ["x", "y"]


def test_to_pandas(mesh8):
    fr, x, cat = _frame(mesh8)
    df = fr.to_pandas()
    assert list(df.columns) == ["x", "cat", "y"]
    assert df["cat"].iloc[0] in ("a", "b", "c")


def test_explicit_domain_on_strings(mesh8):
    fr = Frame.from_arrays({"g": np.array(["b", "a", "zz", "b"])},
                           domains={"g": ["a", "b"]})
    np.testing.assert_array_equal(fr["g"].to_numpy(),
                                  [1, 0, NA_ENUM, 1])  # 'zz' not in domain


def test_na_tokens_are_categories(mesh8):
    fr = Frame.from_arrays({"g": np.array(["NA", "nan", "None", "x"])})
    assert fr["g"].nacnt() == 0
    assert "NA" in fr["g"].domain
    fr2 = Frame.from_arrays({"g": np.array(["a", None, float("nan"), ""],
                                           dtype=object)})
    assert fr2["g"].nacnt() == 3


def test_empty_selection_returns_empty(mesh8):
    fr, *_ = _frame(mesh8)
    assert fr.columns([]) == []


def test_time_column_precision(mesh8):
    t = np.array(["2026-07-29T00:00:00.123", "2026-07-29T00:00:01.456"],
                 dtype="datetime64[ms]")
    fr = Frame.from_arrays({"t": t})
    v = fr["t"]
    assert v.kind == "time"
    back = v.to_numpy()
    np.testing.assert_allclose(back[1] - back[0], 1333.0)  # exact ms delta
    r = v.rollups()
    np.testing.assert_allclose(r["max"] - r["min"], 1333.0)


def test_int_shard_padding(mesh8):
    from h2o_kubernetes_tpu.runtime import shard_rows
    xs = shard_rows(np.arange(13, dtype=np.int32))
    assert np.asarray(xs)[13:].tolist() == [-1, -1, -1]


def test_time_nat_is_na(mesh8):
    t = np.array(["2026-01-01", "NaT", "2026-01-02"], dtype="datetime64[ms]")
    v = Frame.from_arrays({"t": t})["t"]
    assert v.nacnt() == 1
    r = v.rollups()
    np.testing.assert_allclose(r["max"] - r["min"], 86400000.0)


def test_to_pandas_all_na_enum(mesh8):
    fr = Frame.from_arrays({"g": np.array([None, None], dtype=object)})
    df = fr.to_pandas()
    assert df["g"].isna().all()


def test_float_codes_with_nan(mesh8):
    fr = Frame.from_arrays({"c": np.array([0.0, np.nan, 1.0])},
                           domains={"c": ["a", "b"]})
    assert fr["c"].nacnt() == 1
    np.testing.assert_array_equal(fr["c"].to_numpy(), [0, NA_ENUM, 1])
