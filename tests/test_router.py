"""Tenant-sharded fleet router (ISSUE 11 tentpole): placement must be
deterministic, popularity-replicated, and HRW-stable (adding a shard
moves ~1/N of the tail, never a reshuffle); the ShardedPool must
derive child pools from the plan, detect shard death, and re-place an
orphaned tail tenant via a TARGETED registry push; the router must
fail over inside the per-tenant retry budget, honor Retry-After,
serve the typed degraded 503, and keep hedging behind its kill
switch. Real-subprocess legs live in tools/chaos.py
``router-shard-kill``."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from h2o_kubernetes_tpu.operator import (PoolStore, ScorerPoolSpec,
                                         ShardedPool, plan_placement,
                                         shard_preference,
                                         start_router)
from h2o_kubernetes_tpu.operator.autoscale import desired_replicas
from h2o_kubernetes_tpu.operator.probe import probe_json

pytestmark = pytest.mark.chaos

from test_operator import FakeReplica  # noqa: E402 — the scripted
# in-process replica (tests/ is pytest-inserted, not a package)


# ---------------------------------------------------------------------------
# Placement properties (satellite: placement-stability property tests)
# ---------------------------------------------------------------------------

KEYS = [f"m{i:03d}" for i in range(1000)]
SHARDS3 = ["p-s0", "p-s1", "p-s2"]


def test_placement_deterministic():
    a = plan_placement(KEYS, SHARDS3, head=10)
    b = plan_placement(KEYS, SHARDS3, head=10)
    assert a.assignments == b.assignments
    assert a.head_keys == b.head_keys
    # preference order is pure HRW — independent of catalog order
    shuffled = list(reversed(KEYS))
    c = plan_placement(shuffled, SHARDS3, head=0)
    for k in KEYS[10:]:
        assert c.assignments[k] == a.assignments[k]


def test_placement_head_replicated_tail_single():
    plan = plan_placement(KEYS, SHARDS3, head=10, tail_replicas=1)
    for k in KEYS[:10]:
        assert set(plan.assignments[k]) == set(SHARDS3), k
        # failover order still HRW — deterministic, not alphabetical
        assert list(plan.assignments[k]) == shard_preference(k, SHARDS3)
    for k in KEYS[10:]:
        assert len(plan.assignments[k]) == 1, k
        assert plan.assignments[k][0] == shard_preference(k, SHARDS3)[0]
    # tail_replicas=2 doubles the tail footprint
    plan2 = plan_placement(KEYS, SHARDS3, head=10, tail_replicas=2)
    for k in KEYS[10:]:
        assert list(plan2.assignments[k]) == \
            shard_preference(k, SHARDS3)[:2]


def test_placement_tail_spread_balanced():
    """HRW spreads the tail roughly evenly: no shard holds more than
    ~1.25x its fair share of 990 tail keys."""
    plan = plan_placement(KEYS, SHARDS3, head=10)
    counts = {s: 0 for s in SHARDS3}
    for k in KEYS[10:]:
        counts[plan.assignments[k][0]] += 1
    fair = (len(KEYS) - 10) / len(SHARDS3)
    for s, n in counts.items():
        assert 0.75 * fair <= n <= 1.25 * fair, counts


def test_placement_stability_add_and_remove_shard():
    """The rendezvous contract: growing 3 -> 4 shards moves ~1/4 of
    the tail (bounded well under a reshuffle), and keys that do NOT
    move keep their exact assignment; removing a shard moves ONLY the
    keys that lived on it."""
    tail = KEYS[10:]
    p3 = plan_placement(KEYS, SHARDS3, head=10)
    p4 = plan_placement(KEYS, SHARDS3 + ["p-s3"], head=10)
    moved = [k for k in tail if p3.assignments[k] != p4.assignments[k]]
    n = len(SHARDS3) + 1
    assert len(moved) <= 1.5 * len(tail) / n, \
        f"{len(moved)}/{len(tail)} tail keys moved growing to {n}"
    # every mover moved TO the new shard (that is the only legal move)
    assert all(p4.assignments[k] == ("p-s3",) for k in moved)
    # removal: only the removed shard's keys move
    p2 = plan_placement(KEYS, SHARDS3[:2], head=10)
    for k in tail:
        if p3.assignments[k][0] != "p-s2":
            assert p2.assignments[k] == p3.assignments[k], k


def test_placement_validation():
    with pytest.raises(ValueError, match="at least one shard"):
        plan_placement(KEYS, [])
    with pytest.raises(ValueError, match="duplicate shard"):
        plan_placement(KEYS, ["a", "a"])
    with pytest.raises(ValueError, match="duplicate model keys"):
        plan_placement(["k", "k"], SHARDS3)


def test_spec_shard_fields_validate():
    base = dict(name="p", artifact="a", version=1, model_key="m")
    with pytest.raises(ValueError, match="shards"):
        ScorerPoolSpec(**base, shards=0).validate()
    with pytest.raises(ValueError, match="tail_replicas"):
        ScorerPoolSpec(**base, shards=3, tail_replicas=4).validate()
    with pytest.raises(ValueError, match="head_models"):
        ScorerPoolSpec(**base, shards=2, head_models=0).validate()
    with pytest.raises(ValueError, match="head_models"):
        ScorerPoolSpec(**base, head_models=7).validate()
    # legacy pool untouched; sharded pool with sane fields passes
    ScorerPoolSpec(**base).validate()
    ScorerPoolSpec(**base, shards=3, head_models=1,
                   tail_replicas=2).validate()


# ---------------------------------------------------------------------------
# ShardedPool: child derivation + shard death -> targeted re-placement
# ---------------------------------------------------------------------------


class StubRegistry:
    """Records targeted pushes instead of HTTP."""

    def __init__(self, fail: int = 0):
        self.pushes = []
        self._fail = fail

    def push(self, url, name, version, model_key, warm_buckets=None,
             timeout=300.0, inline=None, slo=None):
        if self._fail > 0:
            self._fail -= 1
            raise IOError("stub push failure")
        self.pushes.append((url, name, int(version), model_key, slo))
        return {"model_id": {"name": model_key}}


def _sharded_pool(shards=2, tenants=8, replicas=1, registry=None,
                  **spec_kw):
    store = PoolStore()
    extra = tuple((f"a{i}", 1, f"t{i}") for i in range(1, tenants + 1))
    store.apply(ScorerPoolSpec(
        name="p", artifact="a0", version=1, model_key="m",
        replicas=replicas, shards=shards, head_models=1,
        extra_artifacts=extra, **spec_kw))
    pool = ShardedPool(store, registry or StubRegistry(), "p",
                       replica_factory=FakeReplica)
    return store, pool


def _settle(pool, passes=40):
    for _ in range(passes):
        pool.reconcile_once()
        if pool.converged():
            return True
    return pool.converged()


def test_sharded_pool_child_specs_partition_catalog():
    store, pool = _sharded_pool(shards=2, tenants=8)
    assert sorted(pool.recs) == ["p-s0", "p-s1"]
    s0, _ = store.get("p-s0")
    s1, _ = store.get("p-s1")
    # primary (the head) on BOTH children; the tail partitioned
    assert s0.artifact == s1.artifact == "a0"
    t0 = {e[2] for e in s0.extra_artifacts}
    t1 = {e[2] for e in s1.extra_artifacts}
    assert t0 | t1 == {f"t{i}" for i in range(1, 9)}
    assert not (t0 & t1), "a tail tenant landed on both shards"
    # the child sets match the plan exactly
    for sid, keys in ((s0.name, t0), (s1.name, t1)):
        assert keys == set(pool.plan.keys_for(sid)) - {"m"}
    assert _settle(pool)
    # the routing table covers the whole catalog and every shard has
    # endpoints; shard-aware autoscale keys wired
    table = pool.routing_table()
    assert set(table["keys"]) == {"m"} | t0 | t1
    assert list(table["keys"]["m"]) == \
        shard_preference("m", ["p-s0", "p-s1"])
    assert pool.recs["p-s0"].autoscale_keys == t0 | {"m"}
    st = store.get_status("p")
    assert st["sharded"] and st["converged"]
    assert st["degraded_count"] == 0


def test_shard_death_replaces_tail_via_targeted_push():
    reg = StubRegistry()
    store, pool = _sharded_pool(shards=2, tenants=8, registry=reg)
    assert _settle(pool)
    # kill every replica of shard s0 (without letting the child
    # reconciler replace it yet — the replace sweep runs first, the
    # way a real shard loss looks while backoff/startup is pending)
    dead_sid = "p-s0"
    survivor = "p-s1"
    orphans = set(pool.plan.keys_for(dead_sid)) - {"m"}
    assert orphans, "fixture must place tail tenants on the shard"
    for r in pool.recs[dead_sid].replicas:
        r._alive = False
    assert set(pool.pending_orphans()) == orphans
    moved = pool._replace_once()
    assert moved == len(orphans)
    # targeted: one push per orphan per survivor replica — never the
    # full catalog
    pushed_keys = {p[3] for p in reg.pushes}
    assert pushed_keys == orphans
    surv_urls = {r.url for r in pool.recs[survivor].replicas}
    assert {p[0] for p in reg.pushes} <= surv_urls
    # overrides + routing table route the orphans to the survivor
    for k in orphans:
        assert pool.overrides[k] == (survivor,)
        assert pool.routing_table()["keys"][k][-1] == survivor
    # durable intent: the survivor's child spec now carries them
    s1, _ = store.get(survivor)
    assert orphans <= {e[2] for e in s1.extra_artifacts}
    # events: shard_down + one tenant_replaced per orphan
    kinds = [e["kind"] for e in store.events("p")]
    assert "shard_down" in kinds
    assert kinds.count("tenant_replaced") == len(orphans)
    assert pool.pending_orphans() == []
    # shard-aware autoscale keys follow the tenants
    assert orphans <= pool.recs[survivor].autoscale_keys
    # the dead shard recovers through the normal child convergence;
    # pool reconverges and records it
    assert _settle(pool, passes=60)
    assert "shard_recovered" in [e["kind"] for e in store.events("p")]


def test_replacement_push_failure_retries_next_pass():
    reg = StubRegistry(fail=1)
    store, pool = _sharded_pool(shards=2, tenants=4, registry=reg)
    assert _settle(pool)
    dead_sid = "p-s0"
    orphans = set(pool.plan.keys_for(dead_sid)) - {"m"}
    for r in pool.recs[dead_sid].replicas:
        r._alive = False
    moved1 = pool._replace_once()      # first push fails (stub)
    moved2 = pool._replace_once()      # level-triggered: retried
    assert moved1 + moved2 == len(orphans)
    kinds = [e["kind"] for e in store.events("p")]
    assert "tenant_replace_failed" in kinds
    assert pool.pending_orphans() == []


def test_replacement_state_survives_controller_restart():
    """A restarted ShardedPool resumes overrides + shard history from
    the status it published: the survivors' extended child specs are
    not clobbered by the re-derived plan, and a shard that died
    BEFORE the restart still reads as LOST (not 'converging'), so its
    tenants keep their re-placement."""
    reg = StubRegistry()
    store, pool = _sharded_pool(shards=2, tenants=6, registry=reg)
    assert _settle(pool)
    dead_sid, survivor = "p-s0", "p-s1"
    orphans = set(pool.plan.keys_for(dead_sid)) - {"m"}
    for r in pool.recs[dead_sid].replicas:
        r._alive = False
    pool._replace_once()
    pool._publish_status()
    assert set(pool.overrides) == orphans

    # the "restarted" controller: a fresh ShardedPool over the SAME
    # store (the durable-store restart shape)
    pool2 = ShardedPool(store, reg, "p", replica_factory=FakeReplica)
    assert {k: v for k, v in pool2.overrides.items()} == \
        {k: (survivor,) for k in orphans}
    assert pool2._ever_healthy == {"p-s0", "p-s1"}
    # the re-derived survivor child spec KEPT the re-placed tenants
    s1, _ = store.get(survivor)
    assert orphans <= {e[2] for e in s1.extra_artifacts}
    # and the routing table still routes them through the survivor
    for k in orphans:
        assert pool2.routing_table()["keys"][k][-1] == survivor
    # the pre-restart-dead shard counts as LOST for the fresh
    # controller (it served once, in the previous life) — its tenants
    # are NOT pending re-placement because the overrides cover them
    assert pool2.pending_orphans() == []


def test_autoscale_model_filter_is_shard_aware():
    """The shard whose OWN tenants shed scales; a shard whose tenants
    are idle reads the same /3/Stats sample as no pressure."""
    spec = ScorerPoolSpec(name="p", artifact="a", version=1,
                          model_key="m", replicas=2, min_replicas=1,
                          max_replicas=4)
    sample = {"batcher": {"queue_depth": 0, "shed": 9,
                          "requests": 500},
              "counters": {"deadline_504": 0},
              "models": {"t1": {"shed": 9, "deadline_504": 0,
                                "requests": 400},
                         "t2": {"shed": 0, "deadline_504": 0,
                                "requests": 100}}}
    # shard A owns t1 (the shedding tenant): pressure -> scale up
    prev = desired_replicas(spec, [sample], model_keys={"t1"})[2]
    bumped = {**sample, "models": {**sample["models"],
                                   "t1": {"shed": 12, "deadline_504": 0,
                                          "requests": 450}}}
    n, why, _ = desired_replicas(spec, [bumped], prev,
                                 model_keys={"t1"})
    assert n == 3 and "shed" in why
    # shard B owns t2 (idle): the SAME sample is no pressure for it
    prev = desired_replicas(spec, [sample], model_keys={"t2"})[2]
    n, why, _ = desired_replicas(spec, [bumped], prev,
                                 model_keys={"t2"})
    assert n != 3, f"idle shard scaled up on another shard's shed: {why}"
    # unfiltered keeps the legacy global-counter behavior
    prev = desired_replicas(spec, [sample])[2]
    n, why, _ = desired_replicas(
        spec, [{**bumped, "batcher": {"queue_depth": 0, "shed": 12,
                                      "requests": 600}}], prev)
    assert n == 3


# ---------------------------------------------------------------------------
# Router: stub replica backends over real HTTP
# ---------------------------------------------------------------------------


class _StubReplica:
    """A minimal replica: /3/Stats says ready, POST behavior is
    scripted per test."""

    def __init__(self, on_post=None, ready=True, name="stub"):
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({"ready": stub.ready,
                                   "name": stub.name}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                req_body = self.rfile.read(n) if n else b""
                code, payload, hdrs = stub.on_post(self.path, req_body,
                                                   dict(self.headers))
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (hdrs or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

        self.ready = ready
        self.name = name
        self.posts = []
        self._on_post = on_post
        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.srv.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()

    def on_post(self, path, body, headers):
        self.posts.append((path, body, headers))
        if self._on_post is not None:
            return self._on_post(path, body, headers)
        return 200, {"predict": ["ok"], "served_by": self.name}, None

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


def _post(url, payload=None, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload or {"rows": [[1.0]]}).encode(),
        method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture
def quiet_health(monkeypatch):
    # tests drive sweep_health() explicitly; a fast background sweep
    # racing a deliberate kill would re-classify mid-assertion
    monkeypatch.setenv("H2O_TPU_ROUTER_HEALTH_INTERVAL", "30")


def _router(table):
    srv, router = start_router(table)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    return srv, router, url


def test_router_forwards_and_fails_over(quiet_health):
    a = _StubReplica(name="a", on_post=lambda *args: (
        500, {"msg": "boom"}, None))
    b = _StubReplica(name="b")
    table = {"keys": {"pm": ["s0", "s1"], "tail": ["s1"]},
             "shards": {"s0": [a.url], "s1": [b.url]}}
    srv, router, url = _router(table)
    try:
        # replicated key: the 5xx from shard s0 fails over to s1
        # under one retry token
        code, out, _ = _post(url + "/3/Predictions/models/pm")
        assert code == 200 and out["served_by"] == "b"
        st = router.snapshot()
        assert st["stats"]["retries"] == 1
        assert st["retry_budget"]["granted"] == 1
        assert st["stats"]["relayed_5xx"] == 1
        # single-shard key forwards without touching the budget
        code, out, _ = _post(url + "/3/Predictions/models/tail")
        assert code == 200 and out["served_by"] == "b"
        assert router.snapshot()["stats"]["retries"] == 1
        # readiness reflects shard health
        with urllib.request.urlopen(url + "/readyz", timeout=5) as r:
            assert r.status == 200
    finally:
        router.stop()
        srv.shutdown()
        a.close()
        b.close()


def test_router_transport_failover_on_dead_replica(quiet_health):
    a = _StubReplica(name="a")
    b = _StubReplica(name="b")
    table = {"keys": {"pm": ["s0", "s1"]},
             "shards": {"s0": [a.url], "s1": [b.url]}}
    srv, router, url = _router(table)
    try:
        a.close()       # dies AFTER the health sweep marked it ready
        code, out, _ = _post(url + "/3/Predictions/models/pm")
        assert code == 200 and out["served_by"] == "b"
        st = router.snapshot()["stats"]
        assert st["failovers"] == 1 and st["transport_errors"] == 1
    finally:
        router.stop()
        srv.shutdown()
        b.close()


def test_router_intra_shard_replica_failover(quiet_health,
                                             monkeypatch):
    """A replica that dies between health sweeps must not 503 a
    single-shard tail tenant while a READY sibling replica exists in
    the SAME shard — intra-shard transport failover is free (no
    cross-shard retry token: nothing was processed, and token-gating
    it would starve the tenant on one replica death)."""
    monkeypatch.setenv("H2O_TPU_ROUTER_RETRY_BUDGET", "0")
    a = _StubReplica(name="a")
    b = _StubReplica(name="b")
    table = {"keys": {"tail": ["s0"]},
             "shards": {"s0": [a.url, b.url]}}
    srv, router, url = _router(table)
    try:
        a.close()       # dies AFTER the sweep marked it ready
        ok = 0
        for _ in range(4):   # round-robin: both rotations covered
            code, out, _ = _post(url + "/3/Predictions/models/tail")
            assert code == 200 and out["served_by"] == "b", (code, out)
            ok += 1
        st = router.snapshot()["stats"]
        assert ok == 4
        assert st["retries"] == 0, "intra-shard failover burned tokens"
        assert st["failovers"] >= 1
    finally:
        router.stop()
        srv.shutdown()
        b.close()


def test_child_resize_survives_parent_reapply():
    """A directly-resized child shard (the capacity-zero shape the
    drill uses for a lost node pool) must survive a parent-spec
    reapply that does not touch replicas; an explicit parent resize
    still flows into every shard."""
    store, pool = _sharded_pool(shards=2, tenants=4)
    assert _settle(pool)
    store.apply_update("p-s0", replicas=0)
    # parent change that does NOT touch replicas: child keeps 0
    store.apply_update("p", head_models=1)   # no-op field, gen bump
    pool._ensure_children()
    assert store.get("p-s0")[0].replicas == 0
    # explicit parent resize overrides every child
    store.apply_update("p", replicas=2)
    pool._ensure_children()
    assert store.get("p-s0")[0].replicas == 2
    assert store.get("p-s1")[0].replicas == 2


def test_router_retry_budget_denied(quiet_health, monkeypatch):
    monkeypatch.setenv("H2O_TPU_ROUTER_RETRY_BUDGET", "0")
    a = _StubReplica(name="a", on_post=lambda *args: (
        502, {"msg": "dying shard"}, None))
    b = _StubReplica(name="b")
    table = {"keys": {"pm": ["s0", "s1"]},
             "shards": {"s0": [a.url], "s1": [b.url]}}
    srv, router, url = _router(table)
    try:
        # budget 0 = no cross-shard retries: the 502 is relayed even
        # though a healthy replica shard exists — the dying shard
        # cannot amplify onto it
        code, out, _ = _post(url + "/3/Predictions/models/pm")
        assert code == 502
        st = router.snapshot()
        assert st["stats"]["retries"] == 0
        assert st["stats"]["retry_denied"] == 1
        assert st["retry_budget"]["denied"] >= 1
        assert st["retry_budget"]["granted"] == 0
        assert len(b.posts) == 0, "request leaked past a denied budget"
    finally:
        router.stop()
        srv.shutdown()
        a.close()
        b.close()


def test_router_degraded_typed_503(quiet_health):
    a = _StubReplica(name="a")
    table = {"keys": {"lonely": ["s0"]}, "shards": {"s0": [a.url]}}
    srv, router, url = _router(table)
    try:
        a.close()
        router.sweep_health()       # observe the death
        code, out, hdrs = _post(url + "/3/Predictions/models/lonely")
        assert code == 503
        assert out["hint"] == "placement_pending"
        assert out["model"] == "lonely"
        assert "Retry-After" in hdrs
        assert router.snapshot()["stats"]["degraded_503"] == 1
        # unknown tenant is a 404, not a degraded 503
        code, out, _ = _post(url + "/3/Predictions/models/nope")
        assert code == 404
        # the router itself reads unready with every shard down
        st = probe_json(url, "/readyz", retries=1)
        assert st and st["ready"] is False
    finally:
        router.stop()
        srv.shutdown()


def test_router_relays_429_and_4xx_without_retry(quiet_health):
    a = _StubReplica(name="a", on_post=lambda *args: (
        429, {"msg": "rate limited"}, {"Retry-After": "7"}))
    b = _StubReplica(name="b")
    table = {"keys": {"pm": ["s0", "s1"]},
             "shards": {"s0": [a.url], "s1": [b.url]}}
    srv, router, url = _router(table)
    try:
        code, out, hdrs = _post(url + "/3/Predictions/models/pm")
        # a tenant's own 429 must NOT fail over — retrying a
        # rate-limited tenant on another shard would defeat the limit
        assert code == 429 and hdrs.get("Retry-After") == "7"
        assert router.snapshot()["stats"]["retries"] == 0
        assert len(b.posts) == 0
    finally:
        router.stop()
        srv.shutdown()
        a.close()
        b.close()


def test_router_honors_retry_after_cooldown(quiet_health):
    calls = {"a": 0}

    def a_post(*args):
        calls["a"] += 1
        return 503, {"msg": "draining"}, {"Retry-After": "30"}

    a = _StubReplica(name="a", on_post=a_post)
    b = _StubReplica(name="b")
    table = {"keys": {"pm": ["s0", "s1"]},
             "shards": {"s0": [a.url], "s1": [b.url]}}
    srv, router, url = _router(table)
    try:
        code, out, _ = _post(url + "/3/Predictions/models/pm")
        assert code == 200 and out["served_by"] == "b"
        # the 503's Retry-After put the replica on cooldown: the next
        # request goes straight to s1 without re-dispatching into the
        # draining pod (and without burning another retry token)
        tokens_before = router.snapshot()["retry_budget"]["granted"]
        code, out, _ = _post(url + "/3/Predictions/models/pm")
        assert code == 200 and out["served_by"] == "b"
        assert calls["a"] == 1
        assert router.snapshot()["retry_budget"]["granted"] == \
            tokens_before
    finally:
        router.stop()
        srv.shutdown()
        a.close()
        b.close()


def test_router_deadline_and_slo_forwarding(quiet_health):
    seen = {}

    def a_post(path, body, headers):
        seen.update(headers)
        return 200, {"predict": ["ok"], "served_by": "a"}, None

    a = _StubReplica(name="a", on_post=a_post)
    table = {"keys": {"pm": ["s0"]}, "shards": {"s0": [a.url]}}
    srv, router, url = _router(table)
    try:
        # expired budget: 504 at the front door, zero forwards
        code, out, _ = _post(url + "/3/Predictions/models/pm",
                             headers={"X-H2O-Deadline-Ms": "-1"})
        assert code == 504 and len(a.posts) == 0
        # live budget: the REMAINING ms is forwarded (shrunk, > 0),
        # and the SLO header passes through
        code, out, _ = _post(
            url + "/3/Predictions/models/pm",
            headers={"X-H2O-Deadline-Ms": "5000",
                     "X-H2O-SLO": "interactive"})
        assert code == 200
        low = {k.lower(): v for k, v in seen.items()}
        fwd = float(low["x-h2o-deadline-ms"])
        assert 0 < fwd <= 5000
        assert low["x-h2o-slo"] == "interactive"
        # bad header: 400, not a forward
        code, out, _ = _post(url + "/3/Predictions/models/pm",
                             headers={"X-H2O-Deadline-Ms": "soon"})
        assert code == 400
    finally:
        router.stop()
        srv.shutdown()
        a.close()


def test_router_hedging_kill_switch(quiet_health, monkeypatch):
    slow_gate = threading.Event()

    def slow_post(*args):
        slow_gate.wait(1.0)
        return 200, {"predict": ["ok"], "served_by": "slow"}, None

    a = _StubReplica(name="slow", on_post=slow_post)
    b = _StubReplica(name="fast")
    table = {"keys": {"pm": ["s0", "s1"]},
             "shards": {"s0": [a.url], "s1": [b.url]}}
    srv, router, url = _router(table)
    try:
        # default OFF: the slow primary is simply waited out
        slow_gate.set()
        code, out, _ = _post(url + "/3/Predictions/models/pm",
                             headers={"X-H2O-SLO": "interactive"})
        assert code == 200
        assert router.snapshot()["stats"]["hedges"] == 0
        # armed: the hedge fires after 30ms and the fast shard wins
        slow_gate.clear()
        monkeypatch.setenv("H2O_TPU_ROUTER_HEDGE_MS", "30")
        code, out, _ = _post(url + "/3/Predictions/models/pm",
                             headers={"X-H2O-SLO": "interactive"})
        assert code == 200 and out["served_by"] == "fast"
        st = router.snapshot()
        assert st["stats"]["hedges"] == 1
        assert st["stats"]["hedge_wins"] == 1
        # hedges consume budget tokens (they are load amplification)
        assert st["retry_budget"]["granted"] == 1
        # non-interactive traffic never hedges
        slow_gate.set()
        code, out, _ = _post(url + "/3/Predictions/models/pm")
        assert code == 200
        assert router.snapshot()["stats"]["hedges"] == 1
    finally:
        slow_gate.set()
        router.stop()
        srv.shutdown()
        a.close()
        b.close()


def test_router_hedge_armed_still_fails_over_fast_5xx(quiet_health,
                                                      monkeypatch):
    """Arming the hedge switch must never LOSE failover: a primary
    that answers 5xx INSIDE the hedge window takes the sequential
    path (cooldown + budget-gated retry) and the healthy replica
    shard absorbs the request — not a relayed 5xx."""
    monkeypatch.setenv("H2O_TPU_ROUTER_HEDGE_MS", "200")
    a = _StubReplica(name="a", on_post=lambda *args: (
        503, {"msg": "draining"}, {"Retry-After": "30"}))
    b = _StubReplica(name="b")
    table = {"keys": {"pm": ["s0", "s1"]},
             "shards": {"s0": [a.url], "s1": [b.url]}}
    srv, router, url = _router(table)
    try:
        code, out, _ = _post(url + "/3/Predictions/models/pm",
                             headers={"X-H2O-SLO": "interactive"})
        assert code == 200 and out["served_by"] == "b"
        st = router.snapshot()
        # the fast-failing primary never counts as a hedge, the
        # failover is a normal budget-gated retry, and the 503's
        # Retry-After cooldown was recorded (second request skips a)
        assert st["stats"]["hedges"] == 0
        assert st["stats"]["retries"] == 1
        calls_a = len(a.posts)
        code, out, _ = _post(url + "/3/Predictions/models/pm",
                             headers={"X-H2O-SLO": "interactive"})
        assert code == 200 and out["served_by"] == "b"
        assert len(a.posts) == calls_a, "cooldown not honored"
    finally:
        router.stop()
        srv.shutdown()
        a.close()
        b.close()


def test_sharded_pool_run_picks_up_added_and_removed_shards():
    """A mid-run parent-spec shard-count change must start (and stop)
    child reconciler threads: a shard added at runtime converges and
    serves its tenants; a removed shard's child is retired."""
    store, pool = _sharded_pool(shards=2, tenants=8)
    stop = threading.Event()
    t = threading.Thread(target=pool.run, args=(stop,),
                         kwargs={"interval": 0.02}, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not pool.converged():
            time.sleep(0.05)
        assert pool.converged()
        # grow 2 -> 3: the new shard must get a running reconciler
        # (pods spawned) and the pool reconverges on the new plan
        store.apply_update("p", shards=3)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if "p-s2" in pool.recs and pool.converged() and \
                    pool.plan.shards == ("p-s0", "p-s1", "p-s2"):
                break
            time.sleep(0.05)
        assert "p-s2" in pool.recs, "added shard never materialized"
        assert pool.converged(), store.get_status("p")
        assert pool.recs["p-s2"].replicas, \
            "added shard's reconciler thread never spawned pods"
        table = pool.routing_table()
        assert any("p-s2" in v for v in table["keys"].values())
        # shrink 3 -> 2: the removed shard's child is retired and its
        # tenants live in the re-derived plan of the survivors
        store.apply_update("p", shards=2)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if "p-s2" not in pool.recs and pool.converged():
                break
            time.sleep(0.05)
        assert "p-s2" not in pool.recs
        assert pool.converged()
        assert set(pool.plan.shards) == {"p-s0", "p-s1"}
        covered = {k for sid in ("p-s0", "p-s1")
                   for k in pool.plan.keys_for(sid)}
        assert covered == set(pool.plan.assignments), \
            "a tenant fell out of the catalog on shard removal"
    finally:
        stop.set()
        t.join(timeout=10)
        pool.shutdown(timeout=10)


def test_router_contributions_route_passthrough(quiet_health):
    a = _StubReplica(name="a")
    table = {"keys": {"pm": ["s0"]}, "shards": {"s0": [a.url]}}
    srv, router, url = _router(table)
    try:
        code, out, _ = _post(
            url + "/3/Predictions/models/pm/contributions")
        assert code == 200
        assert a.posts[-1][0] == "/3/Predictions/models/pm/contributions"
    finally:
        router.stop()
        srv.shutdown()
        a.close()


# ---------------------------------------------------------------------------
# Satellites: registry push retry + shared probe helper
# ---------------------------------------------------------------------------


def test_registry_post_retries_transient_5xx(monkeypatch):
    """Satellite: one flaky replica answer during a rollout push must
    be absorbed by the runtime/retry backoff layer instead of
    surfacing as load_failed; permanent 4xx still fails fast."""
    from h2o_kubernetes_tpu.operator.registry import ModelRegistry

    monkeypatch.setenv("H2O_TPU_RETRY_BASE", "0.01")
    calls = {"n": 0}

    def flaky(path, body, headers):
        calls["n"] += 1
        if calls["n"] <= 2:
            return 503, {"msg": "warming"}, {"Retry-After": "0"}
        return 200, {"ok": True}, None

    stub = _StubReplica(on_post=flaky)
    try:
        out = ModelRegistry._post_json(stub.url, "/3/ModelRegistry/load",
                                       {"model_id": "x"}, timeout=10.0)
        assert out == {"ok": True} and calls["n"] == 3
    finally:
        stub.close()
    # 400 = permanent: exactly one attempt, HTTPError propagates
    calls400 = {"n": 0}

    def bad(path, body, headers):
        calls400["n"] += 1
        return 400, {"msg": "unservable"}, None

    stub = _StubReplica(on_post=bad)
    try:
        with pytest.raises(urllib.error.HTTPError):
            ModelRegistry._post_json(stub.url, "/3/ModelRegistry/load",
                                     {"model_id": "x"}, timeout=10.0)
        assert calls400["n"] == 1
    finally:
        stub.close()
    # connection refused (dead replica): retried, then raises IOError
    calls = {"n": 0}
    t0 = time.monotonic()
    with pytest.raises(IOError):
        ModelRegistry._post_json("http://127.0.0.1:9", "/x", {},
                                 timeout=1.0)
    assert time.monotonic() - t0 < 30


def test_probe_json_shared_helper():
    stub = _StubReplica(name="probe")
    try:
        out = probe_json(stub.url, "/3/Stats", retries=3)
        assert out and out["ready"] is True
    finally:
        stub.close()
    # dead endpoint: classified None quickly (refused = fast), after
    # the full retry count
    t0 = time.monotonic()
    assert probe_json("http://127.0.0.1:9", "/3/Stats",
                      retries=3) is None
    assert time.monotonic() - t0 < 10


# ---------------------------------------------------------------------------
# ISSUE 16: hot-shard rebalancing (make-before-break) + failback
# hygiene + the store-backed N-router table. Real-subprocess leg in
# tools/chaos.py ``router-ha-kill``.
# ---------------------------------------------------------------------------


def test_pressure_by_model_attribution():
    from h2o_kubernetes_tpu.operator.autoscale import pressure_by_model

    samples = [
        {"models": {"t1": {"shed": 3, "deadline_504": 2},
                    "t2": {"shed": 0, "deadline_504": 0}}},
        {"models": {"t1": {"shed": 1}, "t3": {"deadline_504": 5}}},
    ]
    assert pressure_by_model(samples) == {"t1": 6, "t2": 0, "t3": 5}
    # restricted to the shard's OWN placed tenants — the attribution
    # that lets the controller name WHICH tenant to move
    assert pressure_by_model(samples, {"t1"}) == {"t1": 6}


def test_move_destination_skips_placed_and_down():
    from h2o_kubernetes_tpu.operator import move_destination

    pref = shard_preference("t9", SHARDS3)
    # first non-placed shard in the tenant's own HRW order
    assert move_destination("t9", SHARDS3,
                            exclude=[pref[0]]) == pref[1]
    # a down candidate is skipped — make-before-break can only make
    # on a shard that can actually verify READY
    healthy = {s: s != pref[1] for s in SHARDS3}
    assert move_destination("t9", SHARDS3, exclude=[pref[0]],
                            healthy=healthy) == pref[2]
    # everywhere excluded or down: the move waits (None), it never
    # picks an arbitrary shard
    assert move_destination("t9", SHARDS3, exclude=SHARDS3) is None
    assert move_destination(
        "t9", SHARDS3, healthy={s: False for s in SHARDS3}) is None


def _pressurize(pool, sid, key, total):
    """Scripted /3/Stats: the hot tenant's CUMULATIVE shed counter on
    every replica of the shard (rebalance works on deltas)."""
    for r in pool.recs[sid].replicas:
        r.stats_payload = {"models": {key: {"shed": total,
                                            "deadline_504": 0}}}


def test_rebalance_moves_hot_tenant_make_before_break(monkeypatch):
    monkeypatch.setenv("H2O_TPU_REBALANCE", "1")
    monkeypatch.setenv("H2O_TPU_REBALANCE_SUSTAIN", "3")
    monkeypatch.setenv("H2O_TPU_REBALANCE_COOLDOWN", "0")
    reg = StubRegistry()
    store, pool = _sharded_pool(shards=3, tenants=9, registry=reg)
    assert _settle(pool)
    # a singly-placed tail tenant is the hot key
    hot = next(k for k in pool.plan.assignments
               if k != "m" and len(pool.plan.assignments[k]) == 1)
    src = pool.plan.assignments[hot][0]
    base = len(reg.pushes)
    # the settle passes took the (idle) baseline snapshot; the three
    # passes below are consecutive positive deltas — the move fires
    # on the SUSTAIN'th hit, not on the first blip
    for i, total in enumerate((5, 11, 19)):
        _pressurize(pool, src, hot, total)
        pool._rebalance_once()
        if i < 2:
            assert not pool.moves, "moved before pressure sustained"
    mv = pool.moves.get(hot)
    assert mv and mv["state"] == "serving" and mv["src"] == src
    dst = mv["dst"]
    assert dst != src and dst in pool.plan.shards
    # make-before-break: the destination's replicas got the targeted
    # artifact push (push returns only once loaded+warmed — that IS
    # the READY verification), and only then did routing change: the
    # destination takes preference position 0 while the source STILL
    # serves
    pushed = [p for p in reg.pushes[base:] if p[3] == hot]
    dst_urls = {r.url for r in pool.recs[dst].replicas}
    assert pushed and {p[0] for p in pushed} <= dst_urls
    pref = pool.routing_table()["keys"][hot]
    assert pref[0] == dst and src in pref
    # load-driven moves are NOT loss-driven overrides (failback must
    # never undo them)
    assert hot not in pool.overrides
    # durable intent: the destination's child spec + autoscale
    # attribution carry the tenant for future spawns
    sdst, _ = store.get(dst)
    assert hot in {e[2] for e in sdst.extra_artifacts}
    assert hot in pool.recs[dst].autoscale_keys
    assert "tenant_move" in [e["kind"] for e in store.events("p")]
    # the break half is DEFERRED: dwell not elapsed -> source stays
    assert pool._retire_moves() == 0
    assert pool.moves[hot]["state"] == "serving"
    # dwell elapsed -> the source retires out of the table, the
    # source child spec, and the autoscale attribution
    monkeypatch.setenv("H2O_TPU_REBALANCE_RETIRE_S", "0")
    assert pool._retire_moves() == 1
    assert pool.moves[hot]["state"] == "retired"
    pref = pool.routing_table()["keys"][hot]
    assert pref[0] == dst and src not in pref
    ssrc, _ = store.get(src)
    assert hot not in {e[2] for e in ssrc.extra_artifacts}
    assert hot not in pool.recs[src].autoscale_keys
    assert "tenant_move_retired" in \
        [e["kind"] for e in store.events("p")]


def test_rebalance_never_breaks_before_make_holds(monkeypatch):
    """A move whose destination dies inside the dwell window must NOT
    retire its source — the tenant would go dark. The retire waits
    until the destination serves again."""
    monkeypatch.setenv("H2O_TPU_REBALANCE", "1")
    monkeypatch.setenv("H2O_TPU_REBALANCE_SUSTAIN", "2")
    monkeypatch.setenv("H2O_TPU_REBALANCE_COOLDOWN", "0")
    monkeypatch.setenv("H2O_TPU_REBALANCE_RETIRE_S", "0")
    store, pool = _sharded_pool(shards=3, tenants=9)
    assert _settle(pool)
    hot = next(k for k in pool.plan.assignments
               if k != "m" and len(pool.plan.assignments[k]) == 1)
    src = pool.plan.assignments[hot][0]
    for i, total in enumerate((5, 11, 19)):
        _pressurize(pool, src, hot, total)
        pool._rebalance_once()
    dst = pool.moves[hot]["dst"]
    for r in pool.recs[dst].replicas:
        r._alive = False
    assert pool._retire_moves() == 0
    assert pool.moves[hot]["state"] == "serving"
    # the source is still in the routing preference (serving window)
    assert src in pool.routing_table()["keys"][hot]
    # destination recovers -> the deferred break completes
    assert _settle(pool, passes=60)
    assert pool.moves[hot]["state"] == "retired"


def test_failback_ages_out_overrides_when_home_recovers(monkeypatch):
    monkeypatch.setenv("H2O_TPU_REBALANCE_FAILBACK_S", "60")
    reg = StubRegistry()
    store, pool = _sharded_pool(shards=2, tenants=6, registry=reg)
    assert _settle(pool)
    dead_sid, survivor = "p-s0", "p-s1"
    orphans = set(pool.plan.keys_for(dead_sid)) - {"m"}
    for r in pool.recs[dead_sid].replicas:
        r._alive = False
    assert pool._replace_once() == len(orphans)
    assert set(pool.overrides) == orphans
    # home still down: the copies stay (failback needs PROVEN health)
    assert pool._failback_once() == 0
    # the shard revives through normal child convergence, but the
    # 60 s dwell keeps the copies — a flapping shard must not bounce
    # its tenants back and forth
    assert _settle(pool, passes=60)
    assert pool._failback_once() == 0
    assert set(pool.overrides) == orphans
    # dwell satisfied (wait -> 0): the override copies age out of
    # routing, the survivor's child spec, and autoscale attribution —
    # without waiting for the next full plan rebuild
    monkeypatch.setenv("H2O_TPU_REBALANCE_FAILBACK_S", "0")
    assert pool._failback_once() == len(orphans)
    assert pool.overrides == {}
    for k in orphans:
        assert list(pool.routing_table()["keys"][k]) == \
            list(pool.plan.assignments[k])
    s1, _ = store.get(survivor)
    assert not (orphans & {e[2] for e in s1.extra_artifacts})
    assert not (orphans & pool.recs[survivor].autoscale_keys)
    kinds = [e["kind"] for e in store.events("p")]
    assert kinds.count("tenant_failback") == len(orphans)


def test_store_routing_table_monotonic_and_last_good(monkeypatch):
    from h2o_kubernetes_tpu.operator import StoreRoutingTable

    monkeypatch.setenv("H2O_TPU_ROUTER_TABLE_INTERVAL", "0")
    store = PoolStore()
    provider = StoreRoutingTable(store, "p")
    # cold: an EMPTY table marked cold (the router's typed-503 input)
    # — never a crash, never a guessed catalog
    t = provider()
    assert t.get("cold") and t["keys"] == {}
    assert provider.generation == 0
    store.publish_routing("p", {"keys": {"m": ["s0"]},
                                "shards": {"s0": ["u0"]}})
    t = provider()
    assert t["table_generation"] == 1 and not t.get("cold")
    # last-good: a store outage serves the previous snapshot —
    # store unavailability degrades freshness, never serving
    real = store.get_routing

    def _boom(name):
        raise IOError("store down")

    monkeypatch.setattr(store, "get_routing", _boom)
    assert provider()["table_generation"] == 1
    assert provider.snapshot()["refresh_errors"] == 1
    # monotonic: a lagging replica's OLDER document is rejected — a
    # deposed controller's file can never roll a router back
    monkeypatch.setattr(store, "get_routing", lambda name: {
        "table_generation": 0, "keys": {}, "shards": {}})
    assert provider()["table_generation"] == 1
    assert provider.snapshot()["stale_rejected"] == 1
    # recovery: newer documents flow again
    monkeypatch.setattr(store, "get_routing", real)
    store.publish_routing("p", {"keys": {"m": ["s1"]},
                                "shards": {"s1": ["u1"]}})
    assert provider()["table_generation"] == 2
    assert provider.snapshot()["generation"] == 2
    assert provider.snapshot()["refreshes"] == 2


def test_router_cold_table_typed_503_then_serves(quiet_health,
                                                 monkeypatch):
    from h2o_kubernetes_tpu.operator import StoreRoutingTable

    monkeypatch.setenv("H2O_TPU_ROUTER_TABLE_INTERVAL", "0")
    store = PoolStore()
    a = _StubReplica(name="a")
    srv, router, url = _router(StoreRoutingTable(store, "p"))
    try:
        # before any controller ever published: typed degraded 503
        # (the router cannot know the catalog, so it must not 404)
        code, out, hdrs = _post(url + "/3/Predictions/models/pm")
        assert code == 503 and out["hint"] == "table_pending"
        assert hdrs.get("Retry-After") == "1"
        # the elected controller publishes; the SAME router serves on
        # its next sweep without a restart — routers are stateless
        store.publish_routing("p", {"keys": {"pm": ["s0"]},
                                    "shards": {"s0": [a.url]}})
        router.sweep_health()
        code, out, _ = _post(url + "/3/Predictions/models/pm")
        assert code == 200 and out["served_by"] == "a"
        assert router.snapshot()["table_provider"]["generation"] == 1
    finally:
        router.stop()
        srv.shutdown()
        a.close()


def test_two_routers_read_same_generation_after_replacement():
    from h2o_kubernetes_tpu.operator import StoreRoutingTable

    reg = StubRegistry()
    store, pool = _sharded_pool(shards=2, tenants=6, registry=reg)
    assert _settle(pool)
    pool._publish_routing()
    p1 = StoreRoutingTable(store, "p")
    p2 = StoreRoutingTable(store, "p")
    g1 = p1()["table_generation"]
    assert g1 >= 1 and p2()["table_generation"] == g1
    assert p1() == p2()
    # a shard loss + re-placement republishes the table exactly once;
    # BOTH stateless providers observe the same new generation — the
    # N-router front door needs no router-to-router coordination
    dead = "p-s0"
    for r in pool.recs[dead].replicas:
        r._alive = False
    pool._replace_once()
    pool._publish_routing()
    g2 = p1()["table_generation"]
    assert g2 > g1
    assert p2()["table_generation"] == g2
    assert p1() == p2()


def test_deposed_controller_stops_new_holder_publishes():
    import time as _time

    from h2o_kubernetes_tpu.operator import StaleGenerationError

    reg = StubRegistry()
    store, pool = _sharded_pool(shards=2, tenants=4, registry=reg)
    assert _settle(pool)
    # this controller reconciles under lease epoch 1
    assert store.acquire_lease("p", "op-a", ttl=0.05) == 1
    pool.lease_epoch = 1
    pool._publish_routing()
    assert not pool.deposed
    pool._publish_status()
    assert store.get_status("p")["lease_epoch"] == 1
    # the lease expires; a standby takes over at epoch 2. The old
    # holder's next publish is FENCED: it marks itself deposed and
    # stops writing (split-brain ends with exactly one writer)
    _time.sleep(0.08)
    assert store.acquire_lease("p", "op-b", ttl=30.0) == 2
    gen = store.get_routing("p")["table_generation"]
    pool._publish_routing()
    assert pool.deposed
    assert store.get_routing("p")["table_generation"] == gen
    assert "controller_deposed" in \
        [e["kind"] for e in store.events("p")]
    # deposed is sticky: further publishes are no-ops, and even a
    # direct store write under the old epoch stays fenced
    pool._publish_routing()
    with pytest.raises(StaleGenerationError):
        store.publish_routing("p", {"keys": {}, "shards": {}},
                              epoch=1)
    # the new holder (a fresh controller over the same store — the
    # takeover shape) publishes under epoch 2 and the table moves on
    pool2 = ShardedPool(store, reg, "p", replica_factory=FakeReplica)
    pool2.lease_epoch = 2
    pool2._publish_routing()
    assert not pool2.deposed
