"""StackedEnsemble + AutoML + Leaderboard tests (reference: hex/ensemble
StackedEnsemble, h2o-automl AutoML/Leaderboard — SURVEY.md §2b C15/C16)."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.automl import AutoML, Leaderboard
from h2o_kubernetes_tpu.models import GBM, GLM, StackedEnsemble

# long-running tier: deselect locally with -m 'not slow'
pytestmark = pytest.mark.slow


def _frame(n=500, seed=11):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)
    y = np.where(x0 + 0.7 * x1 - 0.3 * x2 +
                 rng.normal(scale=0.5, size=n) > 0, "y", "n")
    return h2o.Frame.from_arrays({"x0": x0, "x1": x1, "x2": x2, "y": y})


class TestStackedEnsemble:
    def test_stacking_binomial(self, mesh8):
        fr = _frame()
        common = dict(nfolds=3, fold_assignment="modulo", seed=5)
        b1 = GBM(ntrees=8, max_depth=3, **common).train(
            y="y", training_frame=fr)
        b2 = GLM(family="binomial", **common).train(
            y="y", training_frame=fr)
        se = StackedEnsemble([b1, b2]).train(y="y", training_frame=fr)
        perf = se.model_performance(fr, "y")
        base_auc = max(b1.cross_validation_metrics()["auc"],
                       b2.cross_validation_metrics()["auc"])
        assert perf["auc"] > base_auc - 0.05
        pred = se.predict(fr)
        assert "predict" in pred.names and pred.nrows == fr.nrows

    def test_rejects_no_cv_models(self, mesh8):
        fr = _frame(300)
        m = GBM(ntrees=3, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        with pytest.raises(ValueError, match="nfolds"):
            StackedEnsemble([m]).train(y="y", training_frame=fr)

    def test_rejects_mismatched_folds(self, mesh8):
        fr = _frame(300)
        m1 = GBM(ntrees=3, max_depth=3, nfolds=3,
                 fold_assignment="modulo", seed=1).train(
            y="y", training_frame=fr)
        m2 = GBM(ntrees=3, max_depth=3, nfolds=3,
                 fold_assignment="random", seed=9).train(
            y="y", training_frame=fr)
        with pytest.raises(ValueError, match="fold assignment"):
            StackedEnsemble([m1, m2]).train(y="y", training_frame=fr)


class TestLeaderboard:
    def test_ordering_desc_metric(self):
        lb = Leaderboard("auc", ascending=False)
        lb.add("a", object(), {"auc": 0.7})
        lb.add("b", object(), {"auc": 0.9})
        lb.add("c", object(), {"auc": 0.8})
        assert [r["model_id"] for r in lb.rows] == ["b", "c", "a"]

    def test_ordering_asc_metric(self):
        lb = Leaderboard("rmse", ascending=True)
        lb.add("a", object(), {"rmse": 3.0})
        lb.add("b", object(), {"rmse": 1.0})
        assert lb.rows[0]["model_id"] == "b"


class TestAutoML:
    def test_automl_binomial(self, mesh8):
        fr = _frame(400)
        am = AutoML(max_models=2, nfolds=3, seed=0,
                    include_algos=["glm", "gbm", "stackedensemble"],
                    verbosity=None)
        am.train(y="y", training_frame=fr)
        lb = am.leaderboard.as_list()
        # 2 base models capped; SE(s) extra
        base_rows = [r for r in lb if "StackedEnsemble" not in r["model_id"]]
        assert len(base_rows) == 2
        assert any("StackedEnsemble" in r["model_id"] for r in lb)
        assert am.leader is not None
        assert am.leaderboard.rows[0]["auc"] >= \
            am.leaderboard.rows[-1]["auc"]
        assert am.job.status == "DONE"
        pred = am.predict(fr)
        assert pred.nrows == fr.nrows

    def test_automl_regression_sorts_rmse(self, mesh8):
        rng = np.random.default_rng(2)
        n = 300
        x = rng.normal(size=n).astype(np.float32)
        yv = (2 * x + rng.normal(scale=0.3, size=n)).astype(np.float32)
        fr = h2o.Frame.from_arrays({"x": x, "resp": yv})
        am = AutoML(max_models=2, nfolds=3, seed=1,
                    include_algos=["glm", "gbm"], verbosity=None)
        am.train(y="resp", training_frame=fr)
        assert am.leaderboard.sort_metric == "rmse"
        assert am.leaderboard.rows[0]["rmse"] <= \
            am.leaderboard.rows[-1]["rmse"]

    def test_include_exclude_mutually_exclusive(self):
        with pytest.raises(ValueError):
            AutoML(include_algos=["gbm"], exclude_algos=["glm"])
